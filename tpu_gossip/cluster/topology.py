"""Cluster axis model: the (hosts, devices) mesh the dist engines run on.

The multi-host runtime generalizes the flat 1-D ``"peers"`` device mesh to
a 2-D ``("hosts", "peers")`` mesh whose row-major flattening IS the flat
shard order: shard ``s`` of the 1-D mesh is device ``(s // D, s % D)`` of
the (H, D) mesh, ``jax.lax.axis_index(("hosts", "peers"))`` yields the
same 0..S-1 ids, and a collective over the axis TUPLE executes the same
SPMD program as the flat collective. That flattening invariant is the
whole determinism story: a 2-D-mesh round is bit-identical to the flat
single-host round (and transitively to the local engine where that
contract holds) because it is literally the same program over the same
shard ids — tests/sim/test_cluster.py pins it.

Axis semantics (dist/mesh.py AXIS_KINDS): the fast intra-host ``"peers"``
axis rides ICI, the slow cross-host ``"hosts"`` axis rides DCN. On the
emulated single-process mesh both axes are host RAM — the 2-D shape is
still meaningful because the static wire analyses and the hierarchical
transport (cluster/hier.py) split bytes by axis, and the byte split is
platform-independent.

This module deliberately imports nothing from the rest of the package so
``dist/`` can depend on it without cycles.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

__all__ = [
    "HOST_AXIS",
    "DEVICE_AXIS",
    "make_cluster_mesh",
    "mesh_axes",
    "mesh_hosts",
    "global_put",
]

HOST_AXIS = "hosts"
DEVICE_AXIS = "peers"


def make_cluster_mesh(
    n_devices: int | None = None, hosts: int = 1
) -> Mesh:
    """(hosts, devices) mesh over (the first ``n_devices``) devices.

    ``hosts=1`` returns the flat 1-D ``("peers",)`` mesh the engines have
    always run on; ``hosts=H`` reshapes the same device order row-major to
    (H, n/H) with axes ``("hosts", "peers")`` — under ``jax.distributed``
    each process contributes its local devices as one host row, and the
    single-process emulation reshapes the emulated devices identically.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} available")
    if hosts <= 1:
        return Mesh(np.asarray(devs[:n]), (DEVICE_AXIS,))
    if n % hosts:
        raise ValueError(
            f"--hosts {hosts} does not divide the device count {n} — the "
            f"(hosts, devices) mesh needs equal rows"
        )
    return Mesh(
        np.asarray(devs[:n]).reshape(hosts, n // hosts),
        (HOST_AXIS, DEVICE_AXIS),
    )


def mesh_axes(mesh: Mesh) -> "str | tuple[str, ...]":
    """The collective/sharding axis spec of a cluster mesh.

    The flat mesh keeps its single axis name; the 2-D mesh returns the
    axis TUPLE ``("hosts", "peers")`` — every ``PartitionSpec``,
    ``all_to_all``, ``psum``/``pmax``, ``all_gather`` and ``axis_index``
    in the dist engines takes this value verbatim, which is what makes
    the 2-D program the flat program.
    """
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def mesh_hosts(mesh: Mesh) -> tuple[int, int]:
    """(H, D) of a cluster mesh; the flat mesh is (1, S)."""
    if len(mesh.axis_names) == 1:
        return 1, mesh.size
    return mesh.shape[HOST_AXIS], mesh.shape[DEVICE_AXIS]


def global_put(x, mesh: Mesh, spec) -> jax.Array:
    """Place one host value onto the mesh, multi-process included.

    Single-process this is ``jax.device_put`` with the NamedSharding —
    the path every engine has always taken. Under ``jax.distributed`` the
    mesh spans devices this process cannot address, so the array is built
    shard by shard from the host value via
    ``jax.make_array_from_callback`` instead: every process holds the
    SAME host value (states are initialized from seeds, tables from the
    partition — both deterministic), and each contributes exactly its
    addressable shards.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    dt = getattr(x, "dtype", None)
    if dt is not None and jax.numpy.issubdtype(dt, jax.dtypes.prng_key):
        # key arrays can't round-trip through numpy; place the raw key
        # data (the trailing data dims are never mesh-sharded — key
        # operands are replicated) and re-wrap
        data = global_put(jax.random.key_data(x), mesh, spec)
        return jax.random.wrap_key_data(data, impl=jax.random.key_impl(x))
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )
