"""SimCluster: the tpu-sim transport backend behind the PeerNode API.

The north-star requirement (BASELINE.json): the same Peer/Seed surface, but
the per-process socket loop replaced by the batched device engine. A
SimCluster plays the *seed* role host-side (topology construction = the
power-law subset policy, executed once as a graph build instead of per-
registration handouts) and runs all peers as rows of a
:class:`~tpu_gossip.core.state.SwarmState`. One ``step()`` is one protocol
round for every peer at once (gossip fan-out + dedup + liveness), replacing
wall-clock timers with the round mapping of SURVEY.md §7.4.
"""

from __future__ import annotations

import jax
import numpy as np

from tpu_gossip.compat.wire import Addr
from tpu_gossip.core.state import (
    SwarmConfig, SwarmState, init_swarm, message_slots,
)
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.sim.engine import simulate

__all__ = ["SimCluster"]


class SimCluster:
    """Hosts a whole swarm of tpu-sim PeerNodes.

    Usage::

        cluster = SimCluster(msg_slots=64, fanout=3)
        peers = [PeerNode("10.0.0.%d" % i, 9000, transport="tpu-sim",
                          cluster=cluster) for i in range(1000)]
        cluster.materialize(m=3)          # power-law topology, one build
        peers[0].gossip("hello")          # infect origin
        cluster.step(20)                  # 20 batched rounds
        peers[999].has_seen("hello")      # -> True
    """

    def __init__(
        self,
        *,
        msg_slots: int = 64,
        fanout: int = 3,
        mode: str = "push",
        seed: int = 0,
        dedup_hashes: int = 1,
        **config_kw,
    ) -> None:
        if dedup_hashes < 1:
            raise ValueError("dedup_hashes must be >= 1")
        self._addrs: list[Addr] = []
        self._ids: dict[Addr, int] = {}
        self._msg_slots = msg_slots
        self._fanout = fanout
        self._mode = mode
        self._seed = seed
        # k > 1: Bloom-filter dedup over the same (N, M) bitmap — k hash
        # planes per message (core.state.message_slots). Trades k=1's
        # rumor conflation for the classic Bloom false-positive law; see
        # docs/dedup_semantics.md
        self._dedup_hashes = dedup_hashes
        self._config_kw = config_kw
        self._silent_pending: set[Addr] = set()
        self.cfg: SwarmConfig | None = None
        self.state: SwarmState | None = None
        self._graph = None

    # --- registration (the seed role's registry) ---------------------------

    def register_peer(self, addr: Addr) -> int:
        if addr in self._ids:
            raise ValueError(f"duplicate peer {addr}")
        if self.state is not None:
            raise RuntimeError("cluster already materialized; register first")
        self._ids[addr] = len(self._addrs)
        self._addrs.append(addr)
        return self._ids[addr]

    @property
    def n_peers(self) -> int:
        return len(self._addrs)

    def materialize(self, *, m: int = 3, graph=None) -> None:
        """Build the power-law topology (preferential attachment, the
        intended semantics of reference Seed.py:151-185) and device state.

        Pass ``graph`` (a :class:`~tpu_gossip.core.topology.Graph` over the
        registered peers, e.g. from ``load_graph``) to run an externally
        fixed topology — the conformance path where socket-mode and tpu-sim
        execute the SAME graph (SURVEY.md §7.4)."""
        n = len(self._addrs)
        if graph is not None:
            if graph.n != n:
                raise ValueError(f"graph has {graph.n} nodes, {n} peers registered")
            self._graph = graph
        elif n < m + 2:
            raise ValueError(f"need at least {m + 2} peers, have {n}")
        else:
            rng = np.random.default_rng(self._seed)
            self._graph = build_csr(n, preferential_attachment(n, m=m, rng=rng))
        self.cfg = SwarmConfig(
            n_peers=n,
            msg_slots=self._msg_slots,
            fanout=self._fanout,
            mode=self._mode,
            **self._config_kw,
        )
        self.state = init_swarm(self._graph, self.cfg, key=jax.random.key(self._seed))
        for addr in self._silent_pending:
            self.set_silent(addr, True)

    def _require_state(self) -> SwarmState:
        if self.state is None:
            raise RuntimeError("call materialize() first")
        return self.state

    def _id(self, addr: Addr) -> int:
        return self._ids[addr]

    # --- the PeerNode-facing API -------------------------------------------

    def gossip(self, addr: Addr, text: str) -> None:
        st = self._require_state()
        i = self._id(addr)
        for slot in message_slots(text, self._msg_slots, self._dedup_hashes):
            st.seen = st.seen.at[i, slot].set(True)
            # record first-infection round unless already infected (-1 =
            # never; engine gates SIR recovery on infected_round >= 0)
            if int(st.infected_round[i, slot]) < 0:
                st.infected_round = st.infected_round.at[i, slot].set(
                    int(st.round)
                )

    def has_seen(self, addr: Addr, text: str) -> bool:
        st = self._require_state()
        i = self._id(addr)
        return all(
            bool(st.seen[i, slot])
            for slot in message_slots(text, self._msg_slots, self._dedup_hashes)
        )

    def set_silent(self, addr: Addr, value: bool) -> None:
        if self.state is None:
            (self._silent_pending.add if value else self._silent_pending.discard)(addr)
            return
        self.state.silent = self.state.silent.at[self._id(addr)].set(value)

    def kill(self, addr: Addr) -> None:
        """Crash a peer (connection-dropping death, vs silent-mode)."""
        st = self._require_state()
        st.alive = st.alive.at[self._id(addr)].set(False)

    def is_declared_dead(self, addr: Addr) -> bool:
        st = self._require_state()
        return bool(st.declared_dead[self._id(addr)])

    def neighbors(self, addr: Addr) -> list[Addr]:
        if self._graph is None:
            raise RuntimeError("call materialize() first")
        return sorted(self._addrs[j] for j in self._graph.neighbors(self._id(addr)))

    # --- round loop ---------------------------------------------------------

    def step(self, rounds: int = 1):
        """Advance every peer ``rounds`` protocol rounds (batched on device);
        returns stacked per-round RoundStats (fields shaped (rounds,))."""
        st = self._require_state()
        self.state, stats = simulate(st, self.cfg, rounds)
        return stats

    def coverage(self, text: str) -> float:
        st = self._require_state()
        slots = message_slots(text, self._msg_slots, self._dedup_hashes)
        if len(slots) == 1:
            return float(st.coverage(slots[0]))
        import jax.numpy as jnp

        live = st.alive & ~st.declared_dead
        got = st.seen[:, jnp.asarray(slots)].all(axis=1) & live
        return float(jnp.sum(got) / jnp.maximum(jnp.sum(live), 1))
