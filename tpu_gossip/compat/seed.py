"""Seed node: membership registry, seed mesh, topology, dead-node purge.

Asyncio re-design of the reference's thread-per-connection seed
(reference Seed.py:56-492): one event loop, one coroutine per connection,
explicit state instead of GIL-protected shared dicts. Deliberate fixes of
documented reference quirks (SURVEY.md §2.6):

- rendezvous turn-taking uses a stable hash (zlib.crc32) over the *seed*
  set, so distinct processes agree on the coordinator; the reference used
  the salted builtin ``hash`` over a peer-derived candidate set
  (Seed.py:187-201) which only agrees across processes by luck.
- ``remove_dead_node`` broadcasts the removal once (the reference's
  duplicated tail double-broadcast, Seed.py:393-406); re-broadcast storms
  still terminate via the absent-node early return.
- ``known_peers`` is deduplicated on merge (the reference appends before its
  dedup check, Seed.py:215,227-228).
- subset handout supports the *intended* degree-preferential power-law
  policy (``subset_policy="powerlaw"``, the capability of the dead
  ``powerlaw_connect`` Seed.py:151-185 and demonstrate_powerlaw.py:5-39)
  as well as the reference's literal first-k behavior (``"first"``,
  Seed.py:127-129) for conformance runs.
"""

from __future__ import annotations

import asyncio
import datetime
import os
import random
import zlib

from tpu_gossip.compat import wire
from tpu_gossip.compat.netutil import close_server_best_effort
from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.compat.wire import Addr

__all__ = ["SeedNode"]


def load_config(path: str) -> list[Addr]:
    """Parse ``ip:port`` lines (reference Seed.py:89-108 / Peer.py:51-72)."""
    out: list[Addr] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ip, port = line.rsplit(":", 1)
            out.append((ip, int(port)))
    return out


class ConfigCache:
    """``load_config`` memoized on (mtime_ns, size): registration-path reads
    (``is_my_turn`` runs once per registering peer) cost a stat, not a parse
    — the file only changes when a seed self-registers."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._stamp: tuple[int, int] | None = None
        self._entries: list[Addr] = []

    def entries(self) -> list[Addr]:
        try:
            st = os.stat(self.path)
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            stamp = None
        if stamp != self._stamp:
            self._entries = load_config(self.path)
            self._stamp = stamp
        return self._entries


class SeedNode:
    """Registry node. ``transport="socket"`` only — in tpu-sim mode the seed
    role (bootstrap + topology) is played by :class:`compat.simnet.SimCluster`
    host-side, so a SeedNode is not constructed at all."""

    def __init__(
        self,
        ip: str,
        port: int,
        config_path: str = "config.txt",
        *,
        timing: ProtocolTiming | None = None,
        subset_policy: str = "powerlaw",  # "powerlaw" | "first"
        subset_size: int = 3,
        transport: str = "socket",
        log_dir: str = ".",
        log_stdout: bool = False,
        rng_seed: int | None = None,
    ) -> None:
        if transport != "socket":
            raise ValueError(
                "SeedNode only runs transport='socket'; tpu-sim swarms are "
                "bootstrapped host-side by compat.simnet.SimCluster"
            )
        if subset_policy not in ("powerlaw", "first"):
            raise ValueError(f"unknown subset_policy {subset_policy!r}")
        self.addr: Addr = (ip, port)
        self.config_path = config_path
        self._config_cache = ConfigCache(config_path)
        self.timing = timing or ProtocolTiming()
        self.subset_policy = subset_policy
        self.subset_size = subset_size
        self._rng = random.Random(rng_seed)

        # registry: peers registered at this seed (Seed.py:29-54)
        self.peer_writers: dict[Addr, asyncio.StreamWriter] = {}
        # seed mesh (Seed.py:60): addr -> writer
        self.seed_writers: dict[Addr, asyncio.StreamWriter] = {}
        self.known_seeds: list[Addr] = []
        self.known_peers: list[Addr] = []
        # replicated global topology {peer: set(peers)} (Seed.py:71)
        self.network_topology: dict[Addr, set[Addr]] = {}

        self._server: asyncio.Server | None = None
        self._tasks: list[asyncio.Task] = []
        # every writer ever opened/accepted — duplicate seed-mesh links are
        # not in seed_writers, but must still be closed on stop or the
        # server's wait_closed() deadlocks on their blocked readers
        self._all_writers: list[asyncio.StreamWriter] = []
        self._log_path = os.path.join(log_dir, f"seed_log_{port}.txt")
        self._log_stdout = log_stdout
        self.running = False

    # --- logging (Seed.py:78-87) -------------------------------------------

    def log(self, msg: str) -> None:
        stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
        line = f"[{stamp}] {msg}"
        if self._log_stdout:
            print(f"seed{self.addr}: {line}")
        with open(self._log_path, "a") as f:
            f.write(line + "\n")

    # --- config bootstrap (Seed.py:89-125) ---------------------------------

    def load_and_register_config(self) -> None:
        entries = self._config_cache.entries()
        self.known_seeds = [a for a in entries if a != self.addr]
        # self-registration: append own ip:port if absent (Seed.py:110-125);
        # the cache re-reads on next use (the append changes mtime/size)
        if self.addr not in entries:
            with open(self.config_path, "a") as f:
                f.write(f"{self.addr[0]}:{self.addr[1]}\n")

    # --- subset handout ----------------------------------------------------

    def get_peer_subset(self, exclude: Addr) -> list[Addr]:
        """Neighbors for a newly registering peer.

        "powerlaw": degree-preferential sample (degree from the replicated
        topology, +1 smoothing so degree-0 peers remain reachable) — the
        intended preferential-attachment semantics. "first": the reference's
        insertion-order prefix (Seed.py:127-129).
        """
        candidates = [a for a in self.known_peers if a != exclude]
        k = min(self.subset_size, len(candidates))
        if k == 0:
            return []
        if self.subset_policy == "first":
            return candidates[:k]
        weights = [len(self.network_topology.get(a, ())) + 1 for a in candidates]
        picked: list[Addr] = []
        pool = list(zip(candidates, weights))
        for _ in range(k):
            total = sum(w for _, w in pool)
            r = self._rng.random() * total
            acc = 0.0
            for i, (a, w) in enumerate(pool):
                acc += w
                if r <= acc:
                    picked.append(a)
                    pool.pop(i)
                    break
        return picked

    def is_my_turn(self, new_peer: Addr) -> bool:
        """Rendezvous coordinator election: exactly one of the seeds the peer
        registers with hands out a non-empty subset (intent of
        Seed.py:194-201). Peers contact the first ⌊n/2⌋+1 seeds in config
        file order (Peer.py:80-81), so the electorate is that deterministic
        prefix — electing a seed outside it would drop the handout."""
        entries = self._config_cache.entries()
        quorum = entries[: len(entries) // 2 + 1]
        if self.addr not in quorum:
            return False
        digest = zlib.crc32(str(new_peer).encode())
        return quorum[digest % len(quorum)] == self.addr

    # --- topology maintenance (Seed.py:131-149, 208-232) -------------------

    def merge_topology(self, peer: Addr, subset: list[Addr]) -> None:
        self.network_topology.setdefault(peer, set()).update(subset)
        for other in subset:
            self.network_topology.setdefault(other, set()).add(peer)
        if peer not in self.known_peers:
            self.known_peers.append(peer)
        for other in subset:
            if other not in self.known_peers:
                self.known_peers.append(other)

    def remove_dead_node(self, addr: Addr) -> bool:
        """Purge a dead peer everywhere; returns True if it was present
        (the re-broadcast guard, Seed.py:373-375)."""
        present = addr in self.network_topology or addr in self.known_peers
        if not present:
            return False
        self.network_topology.pop(addr, None)
        for nbrs in self.network_topology.values():
            nbrs.discard(addr)
        if addr in self.known_peers:
            self.known_peers.remove(addr)
        w = self.peer_writers.pop(addr, None)
        if w is not None:
            w.close()
        self.log(f"Removed dead node {addr}")
        return True

    # --- seed mesh ---------------------------------------------------------

    async def _broadcast_to_seeds(self, data: bytes) -> None:
        for addr, w in list(self.seed_writers.items()):
            try:
                w.write(data)
                await w.drain()
            except (ConnectionError, OSError):
                self.seed_writers.pop(addr, None)

    async def _seed_reconnect_loop(self) -> None:
        """Retry lost seed-mesh links forever (Seed.py:336-341)."""
        while self.running:
            self.known_seeds = [
                a for a in self._config_cache.entries() if a != self.addr
            ]
            for addr in self.known_seeds:
                if addr in self.seed_writers:
                    continue
                try:
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection(*addr),
                        timeout=self.timing.connect_timeout,
                    )
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    continue
                # the whole handshake exchange is guarded + timed out: a peer
                # that resets mid-handshake, or accepts and never replies,
                # must cost one sweep iteration — not kill the reconnect loop
                # for the process lifetime or stall the other seeds' retries.
                # The writer joins _all_writers only on success: a bad config
                # entry retried every sweep must not grow the cleanup list
                # unboundedly.
                try:
                    writer.write(wire.encode_seed_handshake(self.addr))
                    await writer.drain()
                    line = (
                        await asyncio.wait_for(
                            reader.readline(), timeout=self.timing.connect_timeout
                        )
                    ).decode(errors="replace")
                    got = wire.decode_seed_handshake(line)
                except (
                    ConnectionError, OSError, asyncio.TimeoutError,
                    # the literal_eval family, same set wire.classify guards
                    ValueError, TypeError, SyntaxError, RecursionError, MemoryError,
                ):
                    writer.close()
                    continue
                except asyncio.CancelledError:
                    writer.close()  # stop() mid-handshake: don't leak the socket
                    raise
                self._all_writers.append(writer)
                self.seed_writers[got] = writer
                self.log(f"Connected to seed {got}")
                t = asyncio.ensure_future(self._line_loop(reader, writer, got, is_seed=True))
                self._tasks.append(t)
            await asyncio.sleep(self.timing.seed_reconnect_period)

    async def _heartbeat_loop(self) -> None:
        """Seed-mesh heartbeat every heartbeat_period (Seed.py:352-356)."""
        while self.running:
            await self._broadcast_to_seeds(wire.encode_heartbeat(self.addr))
            await asyncio.sleep(self.timing.heartbeat_period)

    # --- connection handling ------------------------------------------------

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """First-line dispatch: seed handshake vs peer registration
        (Seed.py:240-299)."""
        self._all_writers.append(writer)
        try:
            line = (await reader.readline()).decode(errors="replace")
        except (ConnectionError, OSError):
            writer.close()
            return
        kind, payload = wire.classify(line)
        if kind == "seed_handshake":
            peer_seed: Addr = payload
            if peer_seed not in self.seed_writers:
                self.seed_writers[peer_seed] = writer
            writer.write(wire.encode_seed_handshake(self.addr))
            writer.write(wire.encode_heartbeat(self.addr))
            await writer.drain()
            self.log(f"Accepted seed {peer_seed}")
            await self._line_loop(reader, writer, peer_seed, is_seed=True)
            return
        # otherwise: peer registration handshake str((ip, port))
        try:
            peer = wire.decode_peer_handshake(line)
        except (ValueError, SyntaxError):
            self.log(f"Unrecognized handshake: {line!r}")
            writer.close()
            return
        await self._register_peer(peer, reader, writer)

    async def _register_peer(
        self, peer: Addr, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if peer in self.peer_writers:
            self.log(f"Duplicate registration from {peer}")
        self.peer_writers[peer] = writer
        self.log(f"Registered peer {peer}")
        # settle so sibling seeds see the registration first (Seed.py:282)
        await asyncio.sleep(self.timing.registration_settle)
        if self.is_my_turn(peer):
            subset = self.get_peer_subset(exclude=peer)
            writer.write(wire.encode_subset(subset))
            await writer.drain()
            self.log(f"Handed subset {subset} to {peer}")
            self.merge_topology(peer, subset)
            await self._broadcast_to_seeds(wire.encode_new_node_update(peer, subset))
        else:
            writer.write(wire.encode_subset([]))
            await writer.drain()
            if peer not in self.known_peers:
                self.known_peers.append(peer)
        await self._line_loop(reader, writer, peer, is_seed=False)

    async def _line_loop(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        who: Addr,
        *,
        is_seed: bool,
    ) -> None:
        """Steady-state reader (Seed.py:415-444) — EOF closes the connection
        (the reference slept forever on EOF, §2.6.6)."""
        while self.running:
            try:
                raw = await reader.readline()
            except (ConnectionError, OSError):
                break
            if not raw:
                break
            kind, payload = wire.classify(raw)
            if kind == "heartbeat":
                pass  # seeds don't track peer liveness timers; peers report deaths
            elif kind == "new_node_update":
                new_peer, subset = payload
                self.merge_topology(new_peer, subset)
            elif kind == "dead_node":
                if self.remove_dead_node(payload):
                    # single re-broadcast (reference double-broadcasts, §2.6.4)
                    await self._broadcast_to_seeds(wire.encode_dead_node(payload))
            elif kind == "empty":
                continue
            else:
                self.log(f"Unrecognized from {who}: {payload!r}")
        if is_seed:
            if self.seed_writers.get(who) is writer:  # duplicates don't evict
                self.seed_writers.pop(who, None)
        else:
            if self.peer_writers.get(who) is writer:
                self.peer_writers.pop(who, None)
        writer.close()

    # --- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self.running = True
        self.load_and_register_config()
        self._server = await asyncio.start_server(self._on_connection, *self.addr)
        self._tasks += [
            asyncio.ensure_future(self._seed_reconnect_loop()),
            asyncio.ensure_future(self._heartbeat_loop()),
        ]
        self.log(f"Seed listening on {self.addr}")

    async def stop(self) -> None:
        self.running = False
        for t in self._tasks:
            t.cancel()
        for w in self._all_writers:
            w.close()
        await close_server_best_effort(self._server)

    def topology_snapshot(self) -> dict[Addr, set[Addr]]:
        return {k: set(v) for k, v in self.network_topology.items()}
