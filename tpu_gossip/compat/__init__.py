"""Socket-compatibility layer: the reference's process-per-node protocol.

Reproduces the reference's capability surface — seed registry bootstrap from
``config.txt``, quorum registration, rendezvous turn-taking, push gossip,
heartbeat/PING liveness, dead-node purge — over asyncio (one event loop per
node instead of the reference's thread-per-connection, SURVEY.md §1), with
the wire formats of SURVEY.md §2.4 and the timing contract of §2.5.

``transport="socket"`` runs real TCP nodes; ``transport="tpu-sim"`` backs
the same PeerNode/SeedNode API with the batched device engine (the
BASELINE.json north-star flag).
"""

from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.compat.seed import SeedNode

__all__ = ["PeerNode", "SeedNode", "ProtocolTiming"]
