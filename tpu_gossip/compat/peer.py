"""Peer node: bootstrap, push gossip with epidemic relay, liveness detector.

Asyncio re-design of the reference's peer (reference Peer.py:12-465).
Same protocol surface — quorum registration against ⌊n/2⌋+1 seeds
(Peer.py:74-84), first-subset latch with settle delay (Peer.py:104-110),
heartbeat broadcast (Peer.py:365-393), stale→PING→grace→dead detector
(Peer.py:298-363), silent-mode fault injection (Peer.py:437-439) — with the
north-star generalization the reference lacks: received gossip is
deduplicated by message id and RELAYED to the peer's other neighbors
(epidemic flooding), where the reference only logs it (Peer.py:286,206).
``gossip_relay=False`` reproduces the reference's one-hop behavior for
conformance runs.

``transport="tpu-sim"`` keeps the same constructor but registers the peer
into a :class:`~tpu_gossip.compat.simnet.SimCluster`, which runs the whole
swarm as batched device rounds (BASELINE.json north star).
"""

from __future__ import annotations

import asyncio
import datetime
import os
import time
from typing import Callable

from tpu_gossip.compat import wire
from tpu_gossip.compat.netutil import close_server_best_effort
from tpu_gossip.compat.seed import load_config
from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.compat.wire import Addr

__all__ = ["PeerNode"]


class _Conn:
    """One live peer link (either direction)."""

    __slots__ = ("writer", "last_hb", "identity")

    def __init__(self, writer: asyncio.StreamWriter, identity: Addr | None):
        self.writer = writer
        self.last_hb = time.monotonic()
        # listening address claimed in heartbeats — an incoming connection's
        # ephemeral port is not the peer's listening port (Peer.py:33-35)
        self.identity = identity


class PeerNode:
    def __init__(
        self,
        ip: str,
        port: int,
        config_path: str = "config.txt",
        *,
        timing: ProtocolTiming | None = None,
        transport: str = "socket",
        cluster=None,  # SimCluster, required for transport="tpu-sim"
        gossip_relay: bool = True,
        relay_mode: str = "immediate",  # "immediate" | "rounds" | "manual" (external push_tick)
        fanout: int = 3,  # neighbors per push tick (relay_mode="rounds")
        log_dir: str = ".",
        log_stdout: bool = False,
        on_gossip: Callable[[str], None] | None = None,
    ) -> None:
        self.addr: Addr = (ip, port)
        self.config_path = config_path
        self.timing = timing or ProtocolTiming()
        self.transport = transport
        self.gossip_relay = gossip_relay
        if relay_mode not in ("immediate", "rounds", "manual"):
            raise ValueError(f"unknown relay_mode {relay_mode!r}")
        self.relay_mode = relay_mode
        self._tick_rng = None  # lazy per-peer RNG for push_tick
        self.fanout = fanout
        self.silent = False
        self.running = False
        self.on_gossip = on_gossip

        if transport == "tpu-sim":
            if cluster is None:
                raise ValueError("transport='tpu-sim' requires cluster=SimCluster(...)")
            self.cluster = cluster
            cluster.register_peer(self.addr)
            return
        if transport != "socket":
            raise ValueError(f"unknown transport {transport!r}")

        # outgoing/incoming links, keyed by connection address
        self.out_conns: dict[Addr, _Conn] = {}
        self.in_conns: dict[Addr, _Conn] = {}
        self.seed_writers: dict[Addr, asyncio.StreamWriter] = {}
        # hash-based gossip dedup (north star; absent in reference)
        self.seen_messages: set[str] = set()
        self.gossip_log: list[str] = []

        self._first_subset: list[Addr] | None = None
        self._subset_received = False
        self._server: asyncio.Server | None = None
        self._tasks: list[asyncio.Task] = []
        self._log_path = os.path.join(log_dir, f"peer_log_{port}.txt")
        self._log_stdout = log_stdout

    # --- logging (Peer.py:40-49) -------------------------------------------

    def log(self, msg: str) -> None:
        stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
        line = f"[{stamp}] {msg}"
        if self._log_stdout:
            print(f"peer{self.addr}: {line}")
        with open(self._log_path, "a") as f:
            f.write(line + "\n")

    # --- fault injection (Peer.py:437-439) ---------------------------------

    def set_silent(self, value: bool = True) -> None:
        """Silent mode: stop heartbeats and PING replies, keep gossiping and
        keep sockets open — a crash-like fault for the failure detector."""
        self.silent = value
        if self.transport == "tpu-sim":
            self.cluster.set_silent(self.addr, value)

    # --- bootstrap (Peer.py:74-118) ----------------------------------------

    async def _bootstrap(self) -> None:
        seeds = [a for a in load_config(self.config_path) if a != self.addr]
        if not seeds:
            raise RuntimeError(f"no seeds in {self.config_path}")
        quorum = len(seeds) // 2 + 1  # ⌊n/2⌋+1, first in file order (Peer.py:80-81)
        for seed_addr in seeds[:quorum]:
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*seed_addr),
                    timeout=self.timing.connect_timeout,
                )
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self.log(f"Seed {seed_addr} unreachable")
                continue
            try:
                writer.write(wire.encode_peer_handshake(self.addr))
                await writer.drain()
            except (ConnectionError, OSError):
                # a seed that resets mid-handshake must not abort bootstrap:
                # the remaining quorum seeds still get contacted and gossip
                # still starts (same guard as the seed-mesh handshake)
                self.log(f"Seed {seed_addr} reset during handshake")
                writer.close()
                continue
            self.seed_writers[seed_addr] = writer
            self._tasks.append(
                asyncio.ensure_future(self._seed_reply_loop(reader, seed_addr))
            )
        # first-subset latch applies after a settle delay so other seeds'
        # replies land first (Peer.py:104-110)
        await asyncio.sleep(self.timing.subset_apply_delay)
        if self._first_subset:
            await self._connect_to_peers(self._first_subset)
        self._subset_received = True
        self._tasks.append(asyncio.ensure_future(self._gossip_generator()))

    async def _seed_reply_loop(self, reader: asyncio.StreamReader, seed_addr: Addr) -> None:
        """Registration reply (pickled subset, bounded read — §2.6.9), then
        pushed topology updates (Peer.py:153-171)."""
        first = True
        while self.running:
            try:
                raw = await reader.read(4096)
            except (ConnectionError, OSError):
                break
            if not raw:
                break
            try:
                subset = wire.decode_subset(raw)
            except Exception:
                self.log(f"Seed {seed_addr} says: {raw[:120]!r}")
                continue
            if first and not self._subset_received and self._first_subset is None:
                self._first_subset = subset  # only the first subset is latched
                self.log(f"First subset from {seed_addr}: {subset}")
            elif subset:
                await self._connect_to_peers(subset)  # later pushed updates
            first = False

    # --- peer links (Peer.py:173-296) --------------------------------------

    async def _connect_to_peers(self, subset: list[Addr]) -> None:
        for peer in subset:
            if peer == self.addr or peer in self.out_conns:
                continue
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*peer),
                    timeout=self.timing.connect_timeout,
                )
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self.log(f"Peer {peer} unreachable")
                continue
            conn = _Conn(writer, identity=peer)
            self.out_conns[peer] = conn
            if not self.silent:
                writer.write(wire.encode_heartbeat(self.addr))
                await writer.drain()
            self._tasks.append(
                asyncio.ensure_future(self._peer_line_loop(reader, conn, peer, outgoing=True))
            )

    async def _on_peer_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn_addr: Addr = writer.get_extra_info("peername")
        conn = _Conn(writer, identity=None)
        self.in_conns[conn_addr] = conn
        await self._peer_line_loop(reader, conn, conn_addr, outgoing=False)

    async def _peer_line_loop(
        self, reader: asyncio.StreamReader, conn: _Conn, key: Addr, *, outgoing: bool
    ) -> None:
        while self.running:
            try:
                raw = await reader.readline()
            except (ConnectionError, OSError):
                break
            if not raw:
                break
            kind, payload = wire.classify(raw)
            if kind == "heartbeat":
                conn.identity = payload  # reported identity (Peer.py:194-199)
                conn.last_hb = time.monotonic()
            elif kind == "ping":
                if not self.silent:  # Peer.py:201-205
                    conn.writer.write(wire.encode_heartbeat(self.addr))
                    try:
                        await conn.writer.drain()
                    except (ConnectionError, OSError):
                        break
            elif kind == "gossip_or_text":
                await self._on_gossip_line(payload, from_conn=conn)
            elif kind == "malformed":
                self.log(f"Malformed line: {payload!r}")
            elif kind == "empty":
                continue
        (self.out_conns if outgoing else self.in_conns).pop(key, None)
        conn.writer.close()

    # --- gossip (Peer.py:395-408, generalized) ------------------------------

    async def _on_gossip_line(self, line: str, from_conn: _Conn | None) -> None:
        msg_id = wire.gossip_message_id(line)
        if msg_id in self.seen_messages:
            return  # hash-based dedup: re-receipt is a no-op
        self.seen_messages.add(msg_id)
        self.gossip_log.append(msg_id)
        self.log(f"Gossip: {msg_id}")
        if self.on_gossip is not None:
            self.on_gossip(msg_id)
        if self.gossip_relay and self.relay_mode == "immediate":
            await self._broadcast_gossip(msg_id, exclude=from_conn)
        # relay_mode="rounds": _push_tick_loop handles dissemination;
        # relay_mode="manual": the harness drives push_tick() itself

    async def _broadcast_gossip(self, line: str, exclude: _Conn | None = None) -> None:
        data = (line + "\n").encode()
        conns = list(self.out_conns.items()) + list(self.in_conns.items())
        for key, conn in conns:
            if conn is exclude:
                continue
            try:
                conn.writer.write(data)
                await conn.writer.drain()
            except (ConnectionError, OSError):
                self.out_conns.pop(key, None)
                self.in_conns.pop(key, None)

    async def _gossip_generator(self) -> None:
        """Generate gossip_count messages, one per gossip_period
        (Peer.py:396-408: 10 messages / 5 s; identity format per
        wire.encode_gossip — port term added for dedup uniqueness)."""
        for count in range(1, self.timing.gossip_count + 1):
            if not self.running:
                return
            stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
            line = wire.encode_gossip(stamp, self.addr[0], self.addr[1], count).decode().strip()
            self.seen_messages.add(line)
            self.gossip_log.append(line)
            await self._broadcast_gossip(line)
            await asyncio.sleep(self.timing.gossip_period)

    def send_to_seeds(self, text: str) -> int:
        """Forward a raw operator line to every connected seed — the
        reference's stdin passthrough (Peer.py:441-442), which the seed
        consumes as an "Unrecognized" line (Seed.py:440-441). Returns the
        number of seeds written to."""
        sent = 0
        # frame with a newline: our seed parses its streams line-wise
        # (readline), unlike the reference's raw recv() chunks — an
        # unframed write would sit in the buffer and merge with the next
        # protocol line into one garbage message
        data = text.encode() if text.endswith("\n") else text.encode() + b"\n"
        for seed_addr, writer in list(self.seed_writers.items()):
            try:
                writer.write(data)
                sent += 1
            except (ConnectionError, OSError):
                self.log(f"Seed {seed_addr} unreachable for passthrough")
        return sent

    def gossip(self, text: str) -> None:
        """Inject an application message into the swarm."""
        if self.transport == "tpu-sim":
            self.cluster.gossip(self.addr, text)
            return
        self.seen_messages.add(text)
        self.gossip_log.append(text)
        if self.relay_mode == "immediate":
            asyncio.ensure_future(self._broadcast_gossip(text))
        # rounds mode: the next push tick disseminates it

    async def push_tick(self, messages: list[str] | None = None) -> None:
        """ONE round of round-gated push gossip: push everything seen to
        ``fanout`` uniformly sampled neighbors — the socket-side twin of the
        engine's push round (sim/engine.py). Driven by :meth:`_push_tick_loop`
        on a wall-clock cadence (relay_mode="rounds"), or externally by a
        barrier-stepping harness (relay_mode="manual") so a "round" is an
        exact barrier rather than a wall-clock bin (conformance tests).

        ``messages`` lets the harness pass a seen-set snapshot taken at the
        barrier start, so messages received DURING the barrier are not
        relayed until the next round (simultaneous-round semantics, matching
        the engine where all peers push state as of round start)."""
        if self._tick_rng is None:
            import random as _random

            self._tick_rng = _random.Random(self.addr[1])
        rng = self._tick_rng
        conns = list(self.out_conns.values()) + list(self.in_conns.values())
        if messages is None:
            messages = list(self.seen_messages)
        if not conns or not messages:
            return
        for msg in messages:
            data = (msg + "\n").encode()
            for conn in rng.choices(conns, k=min(self.fanout, len(conns))):
                try:
                    conn.writer.write(data)
                    await conn.writer.drain()
                except (ConnectionError, OSError):
                    pass

    async def _push_tick_loop(self) -> None:
        while self.running:
            await asyncio.sleep(self.timing.gossip_period)
            await self.push_tick()

    # --- liveness (Peer.py:298-393) ----------------------------------------

    async def _heartbeat_loop(self) -> None:
        while self.running:
            if not self.silent:
                data = wire.encode_heartbeat(self.addr)
                for key, conn in list(self.out_conns.items()) + list(self.in_conns.items()):
                    try:
                        conn.writer.write(data)
                        await conn.writer.drain()
                    except (ConnectionError, OSError):
                        self.out_conns.pop(key, None)
                        self.in_conns.pop(key, None)
            await asyncio.sleep(self.timing.heartbeat_period)

    async def _detector_loop(self) -> None:
        """Stale → PING → grace → declare dead (Peer.py:298-363).

        The sweep is batched: every stale connection is PINGed up front and
        ONE grace period covers them all, so sweep time is O(1) in the stale
        count. (The reference serializes the grace per stale peer —
        Peer.py:298-363 — making k simultaneous failures take k grace
        periods to clear; that is a bug band this build fixes on purpose,
        like the rendezvous and re-broadcast quirks.)"""
        while self.running:
            await asyncio.sleep(self.timing.detect_period)
            now = time.monotonic()
            suspects: list[tuple[Addr, _Conn, dict[Addr, _Conn]]] = []
            for conns in (self.out_conns, self.in_conns):
                for key, conn in list(conns.items()):
                    if now - conn.last_hb <= self.timing.heartbeat_timeout:
                        continue
                    try:
                        conn.writer.write(wire.encode_ping())
                        await conn.writer.drain()
                    except (ConnectionError, OSError):
                        await self._declare_dead(key, conn, conns)
                        continue
                    suspects.append((key, conn, conns))
            if not suspects:
                continue
            await asyncio.sleep(self.timing.ping_grace)
            for key, conn, conns in suspects:
                # the key may have been re-bound (reconnect) or removed
                # (heartbeat-loop error path) during the shared grace — only
                # the exact suspected connection may be declared dead
                if conns.get(key) is not conn:
                    continue
                # a heartbeat during the grace advances last_hb (Peer.py:309)
                if time.monotonic() - conn.last_hb > self.timing.heartbeat_timeout:
                    await self._declare_dead(key, conn, conns)

    async def _declare_dead(self, key: Addr, conn: _Conn, conns: dict[Addr, _Conn]) -> None:
        identity = conn.identity or key
        self.log(f"Declared dead: {identity}")
        data = wire.encode_dead_node(identity)
        for seed_addr, w in list(self.seed_writers.items()):
            try:
                w.write(data)
                await w.drain()
            except (ConnectionError, OSError):
                self.seed_writers.pop(seed_addr, None)
        conns.pop(key, None)
        conn.writer.close()

    # --- lifecycle ----------------------------------------------------------

    async def start_detached(self) -> None:
        """Start server + protocol loops WITHOUT seed bootstrap — for
        harnesses that wire an explicit topology via :meth:`connect_to`
        (e.g. the socket-vs-tpu-sim conformance runs on a fixed graph)."""
        self.running = True
        self._server = await asyncio.start_server(self._on_peer_connection, *self.addr)
        self._subset_received = True
        self._tasks += [
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._detector_loop()),
        ]
        if self.gossip_relay and self.relay_mode == "rounds":
            self._tasks.append(asyncio.ensure_future(self._push_tick_loop()))

    async def connect_to(self, peers: list[Addr]) -> None:
        """Dial the given peers directly (harness/topology-injection path)."""
        await self._connect_to_peers(peers)

    async def start(self) -> None:
        if self.transport == "tpu-sim":
            self.running = True
            return
        self.running = True
        self._server = await asyncio.start_server(self._on_peer_connection, *self.addr)
        await self._bootstrap()
        self._tasks += [
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._detector_loop()),
        ]
        if self.gossip_relay and self.relay_mode == "rounds":
            self._tasks.append(asyncio.ensure_future(self._push_tick_loop()))
        self.log(f"Peer up on {self.addr}")

    async def stop(self) -> None:
        self.running = False
        if self.transport == "tpu-sim":
            return
        for t in self._tasks:
            t.cancel()
        for conn in list(self.out_conns.values()) + list(self.in_conns.values()):
            conn.writer.close()
        for w in self.seed_writers.values():
            w.close()
        await close_server_best_effort(self._server)

    # --- introspection -----------------------------------------------------

    @property
    def neighbors(self) -> list[Addr]:
        if self.transport == "tpu-sim":
            return self.cluster.neighbors(self.addr)
        out = list(self.out_conns.keys())
        out += [c.identity for c in self.in_conns.values() if c.identity]
        return sorted(set(out))

    def has_seen(self, msg_id: str) -> bool:
        if self.transport == "tpu-sim":
            return self.cluster.has_seen(self.addr, msg_id)
        return msg_id in self.seen_messages
