"""The reference's wall-clock protocol contract, as one scalable dataclass.

All constants are hard-coded literals in the reference (SURVEY.md §2.5);
here they scale together so integration tests can run the identical state
machine 100× faster (`ProtocolTiming.scaled(0.01)`).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ProtocolTiming:
    """Defaults reproduce SURVEY.md §2.5 exactly."""

    heartbeat_period: float = 15.0  # peer→peer + seed→seed heartbeat (Peer.py:393, Seed.py:356)
    detect_period: float = 10.0  # failure-detector sweep (Peer.py:363)
    heartbeat_timeout: float = 30.0  # stale threshold (Peer.py:299)
    ping_grace: float = 2.0  # post-PING wait before declaring dead (Peer.py:300)
    gossip_period: float = 5.0  # gossip generation tick (Peer.py:396-408)
    gossip_count: int = 10  # messages generated per peer (Peer.py:396)
    seed_reconnect_period: float = 15.0  # seed-mesh retry sweep (Seed.py:341)
    registration_settle: float = 1.0  # seed-side sleep before subset (Seed.py:282)
    subset_apply_delay: float = 1.0  # peer-side first-subset delay (Peer.py:108)
    connect_timeout: float = 5.0  # all TCP connects (Peer.py:91,245; Seed.py:305)
    topology_dump_period: float = 30.0  # seed topology print (Seed.py:486)

    def scaled(self, factor: float) -> "ProtocolTiming":
        """Uniformly speed up (factor < 1) every duration; counts unchanged."""
        return ProtocolTiming(
            **{
                f.name: (
                    getattr(self, f.name) * factor
                    if f.type == "float"
                    else getattr(self, f.name)
                )
                for f in dataclasses.fields(self)
            }
        )
