"""Small shared asyncio-transport helpers for the socket compat layer."""

from __future__ import annotations

import asyncio

__all__ = ["close_server_best_effort"]


async def close_server_best_effort(
    server: asyncio.AbstractServer | None, timeout: float = 5.0
) -> None:
    """Close a listening server without ever hanging shutdown.

    Python 3.12's ``Server.wait_closed()`` waits for every connection to
    fully close, so one straggler mid-handshake could hang ``stop()``
    forever; node shutdown is best-effort by design (the reference's is a
    daemon-thread process exit, reference Peer.py:417-446).
    """
    if server is None:
        return
    server.close()
    try:
        await asyncio.wait_for(server.wait_closed(), timeout=timeout)
    except (asyncio.TimeoutError, TimeoutError):
        pass
