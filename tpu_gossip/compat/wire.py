"""Wire-protocol codecs: the de-facto API between reference processes.

One function pair per message of SURVEY.md §2.4. Text messages are
newline-terminated ASCII with Python-literal addresses parsed via
``ast.literal_eval`` (reference Peer.py:194, Seed.py:251,274); peer subsets
are pickled lists with a trailing newline (Seed.py:286,290). Unpickling is
restricted to tuples/lists/ints/strings — the reference calls bare
``pickle.loads`` on network bytes (Peer.py:103), which we do not reproduce.

The subset framing quirk is reproduced deliberately (SURVEY.md §2.6.9): the
payload is read with a single bounded ``read()`` and ``pickle`` ignores the
trailing bytes; there is no length prefix on the wire.
"""

from __future__ import annotations

import ast
import io
import pickle
from typing import Any

Addr = tuple[str, int]

SEED_HANDSHAKE_PREFIX = "I am seed|"
HEARTBEAT_PREFIX = "Heartbeat from "
DEAD_NODE_PREFIX = "Dead Node: "
NEW_NODE_PREFIX = "NewNodeUpdate|"
PING = "PING"


def _parse_addr(text: str) -> Addr:
    val = ast.literal_eval(text.strip())
    if (
        not isinstance(val, tuple)
        or len(val) != 2
        or not isinstance(val[0], str)
        or not isinstance(val[1], int)
    ):
        raise ValueError(f"not an (ip, port) tuple: {text!r}")
    return val


# --- peer → seed registration handshake (Peer.py:95-97 → Seed.py:273-274) ---

def encode_peer_handshake(addr: Addr) -> bytes:
    return (str(addr) + "\n").encode()


def decode_peer_handshake(line: str) -> Addr:
    return _parse_addr(line)


# --- seed ↔ seed handshake (Seed.py:307-308, 261-262) -----------------------

def encode_seed_handshake(addr: Addr) -> bytes:
    return (SEED_HANDSHAKE_PREFIX + str(addr) + "\n").encode()


def decode_seed_handshake(line: str) -> Addr:
    if not line.startswith(SEED_HANDSHAKE_PREFIX):
        raise ValueError(f"not a seed handshake: {line!r}")
    return _parse_addr(line[len(SEED_HANDSHAKE_PREFIX):])


# --- peer subset: seed → registering peer (Seed.py:286,290) -----------------

class _SubsetUnpickler(pickle.Unpickler):
    """Data-only unpickling: no global lookups at all."""

    def find_class(self, module: str, name: str):
        raise pickle.UnpicklingError(f"forbidden global {module}.{name}")


def encode_subset(subset: list[Addr]) -> bytes:
    return pickle.dumps(list(subset)) + b"\n"


def decode_subset(payload: bytes) -> list[Addr]:
    """Restricted-unpickle a subset; trailing bytes ignored (§2.6.9)."""
    got = _SubsetUnpickler(io.BytesIO(payload)).load()
    if not isinstance(got, list):
        raise ValueError("subset payload is not a list")
    return [_parse_addr(str(tuple(e))) for e in got]


# --- inter-seed topology replication (Seed.py:203-206 → 432-433) ------------

def encode_new_node_update(new_peer: Addr, subset: list[Addr]) -> bytes:
    """Known framing limitation (inherited from the reference's
    '|'-separated format, Seed.py:203-206): an ip string containing '|'
    is not representable — the decoder splits on the first '|' and will
    reject such a line as malformed rather than mis-parse it."""
    return f"{NEW_NODE_PREFIX}{new_peer}|{list(subset)}\n".encode()


def decode_new_node_update(line: str) -> tuple[Addr, list[Addr]]:
    if not line.startswith(NEW_NODE_PREFIX):
        raise ValueError(f"not a NewNodeUpdate: {line!r}")
    peer_part, subset_part = line[len(NEW_NODE_PREFIX):].split("|", 1)
    subset = ast.literal_eval(subset_part.strip())
    return _parse_addr(peer_part), [_parse_addr(str(tuple(e))) for e in subset]


# --- heartbeat / liveness (Peer.py:368, Seed.py:354-355) --------------------

def encode_heartbeat(addr: Addr) -> bytes:
    return (HEARTBEAT_PREFIX + str(addr) + "\n").encode()


def decode_heartbeat(line: str) -> Addr:
    # the reference splits on "from" + literal_eval (Peer.py:194-199)
    if HEARTBEAT_PREFIX not in line:
        raise ValueError(f"not a heartbeat: {line!r}")
    return _parse_addr(line.split("from", 1)[1])


def encode_ping() -> bytes:
    return (PING + "\n").encode()


# --- dead-node report (Peer.py:311-313 → Seed.py:358-406) -------------------

def encode_dead_node(addr: Addr) -> bytes:
    return (DEAD_NODE_PREFIX + str(addr) + "\n").encode()


def decode_dead_node(line: str) -> Addr:
    if not line.startswith(DEAD_NODE_PREFIX):
        raise ValueError(f"not a dead-node report: {line!r}")
    return _parse_addr(line[len(DEAD_NODE_PREFIX):])


# --- gossip payload (Peer.py:398-404) ---------------------------------------

def encode_gossip(timestamp: str, ip: str, port: int, count: int) -> bytes:
    """Gossip line '{ts}:{ip}:{port}:{count}'.

    Deliberate divergence from the reference's '{ts}:{ip}:{count}'
    (Peer.py:398-404): with hash-based dedup (which the reference lacks) the
    line is the message identity, and the reference format collides across
    peers sharing an ip + timestamp second; the port term makes identities
    unique per origin.
    """
    return f"{timestamp}:{ip}:{port}:{count}\n".encode()


def gossip_message_id(line: str) -> str:
    """The dedup identity of a gossip line: the full text."""
    return line.strip()


# --- dispatch ---------------------------------------------------------------

def classify(line: str | bytes) -> tuple[str, Any]:
    """Map an inbound line to (kind, decoded payload). TOTAL: never raises.

    Kinds: seed_handshake | heartbeat | ping | dead_node | new_node_update |
    gossip_or_text (everything else — the reference logs unknowns,
    Peer.py:206,286, Seed.py:440-441) | malformed (a recognized prefix whose
    payload fails to parse) | empty.

    Network bytes are untrusted, and the reader loops (compat/peer.py,
    compat/seed.py) dispatch straight off this function: if it raised, one
    malformed address (or non-UTF-8 bytes, accepted here via
    ``errors="replace"``) would kill the connection's reader and leak the
    socket — the reference has exactly that latent bug (its per-connection
    thread dies in ``ast.literal_eval``, Peer.py:194-199). ``malformed``
    lines are for logging, like unknown text.
    """
    if isinstance(line, bytes):
        line = line.decode(errors="replace")
    s = line.strip()
    if not s:
        return "empty", None
    if s == PING:
        return "ping", None
    try:
        if s.startswith(SEED_HANDSHAKE_PREFIX):
            return "seed_handshake", decode_seed_handshake(s)
        if s.startswith(HEARTBEAT_PREFIX):
            return "heartbeat", decode_heartbeat(s)
        if s.startswith(DEAD_NODE_PREFIX):
            return "dead_node", decode_dead_node(s)
        if s.startswith(NEW_NODE_PREFIX):
            return "new_node_update", decode_new_node_update(s)
    except (ValueError, TypeError, SyntaxError, RecursionError, MemoryError):
        # ValueError covers _parse_addr rejects; TypeError covers subset
        # entries that aren't tuple-able (e.g. "NewNodeUpdate|('a',1)|5");
        # SyntaxError/RecursionError/MemoryError cover ast.literal_eval on
        # hostile payloads
        return "malformed", s
    return "gossip_or_text", s
