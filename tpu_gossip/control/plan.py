"""Adaptive protocol control plans: a bounded fanout/mix policy, compiled
host-side.

Every engine before this plane pushed with a STATIC fanout ``m`` every
round. *Push is Fast on Sparse Random Graphs* (PAPERS.md) shows that
overpays in the early and late epidemic phases — the useful ``m`` is a
function of the epidemic's phase — and *Reliable Probabilistic Gossip
over Large-Scale Random Topologies* (PAPERS.md) shows it under-delivers
exactly when loss and partitions bite. A :class:`ControlSpec` is the
jit-static description of the feedback policy that closes that loop —
the control twin of :class:`~tpu_gossip.faults.CompiledScenario`,
:class:`~tpu_gossip.growth.CompiledGrowth` and
:class:`~tpu_gossip.traffic.CompiledStream`:

- **fanout table** — the policy is a bounded TABLE of effective fanouts
  ``[lo, lo+1, .., hi]``; the state carries one int32 cursor
  (``SwarmState.control_lvl``) indexing it. Per round the AIMD-style
  update (control/engine.py) widens the level when the observed delivery
  signals fall below ``target_ratio`` (loss bites, stream slots lag) and
  shrinks it multiplicatively when the duplicate rate saturates — the
  late-epidemic regime where every push is a re-delivery.
- **push↔push-pull mix** — in ``push_pull`` mode the pull half costs one
  request per receptive peer per round regardless of coverage, and a
  pull succeeds for a given message with probability ≈ that message's
  current coverage — worthless during the pure ramp, decisive on the
  saturated tail. The mix is therefore THREE gates OR-ed: the level
  table keeps anti-entropy on at-or-below the static baseline fanout (so
  the zero-adjustment spec is exactly the uncontrolled push_pull); a
  lag-free knee gate switches it on while some live message's coverage
  sits in ``[pull_knee, target)`` (``pull_knee`` > 0 makes the opening
  ramp pure push); and the cursor's stress bit latches it on after any
  under-delivery round. Orthogonally, the **needy-pull** gate
  (``pull_needy``, on by default for active bounds) stops SATED peers —
  nothing live missing — from issuing their request at all: every seen
  bit lives on a leased slot, so the skipped pull could not have
  delivered anything, and the late-phase request flood collapses to the
  stragglers who need it. The table's one extra **stress rung** — the
  widest fanout WITH the pull half on — sits above the clean levels and
  is reachable only by the under-delivery widening path. The clean-start
  cursor begins on the widest clean level, one below the rung.
- **PeerSwap neighbor refresh** — every ``refresh_every`` rounds each
  live re-wired peer swaps one of its fresh-edge slots for a new
  degree-preferential endpoint draw (PAPERS.md's PeerSwap: continuous
  randomized neighbor exchange keeps a long-lived overlay's randomness
  guarantees). The swap rides the EXISTING re-wiring plane —
  ``rewire_targets`` entries are replaced in place with degree-credit
  bookkeeping preserved — and draws from the registered
  ``CONTROL_STREAM_SALT`` stream at global shape, so controlled runs
  stay bit-identical local vs sharded.

The spec carries NO per-node tables — it is layout-blind by
construction, so one compile serves every engine (and survives an epoch
re-partition, unlike scenario node masks or growth admit rows).
``control=None`` compiles the whole stage out and a zero-adjustment
spec (``lo == hi == fanout``, ``refresh_every=0``) reproduces the
uncontrolled protocol trajectory bit for bit (both test-pinned,
tests/sim/test_control.py).
"""

from __future__ import annotations

import dataclasses

import jax

__all__ = [
    "ControlError",
    "ControlSpec",
    "compile_control",
]


class ControlError(ValueError):
    """A control config that cannot mean what it says (compile time)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ControlSpec:
    """A feedback-control policy compiled to device tables.

    Traced leaves carry the bounded policy tables and thresholds; static
    fields decide trace structure (table length, draw width, refresh
    cadence) — one compile serves the whole run on every engine. The
    schedule cursor is ``SwarmState.control_lvl`` (int32 scalar, -1 =
    uninitialized: the first controlled round starts at the WIDEST
    level, the epidemic-growth regime), so mid-run checkpoints resume
    the policy bit-exactly with zero host bookkeeping.
    """

    fanout_table: jax.Array  # int32 (L,) — effective fanout per level
    pull_table: jax.Array  # bool (L,) — run the pull half at this level
    target_ratio: jax.Array  # f32 () — the declared delivery-ratio target
    sat_dup: jax.Array  # f32 () — duplicate-rate saturation threshold
    pull_knee: jax.Array  # f32 () — slot coverage where anti-entropy pays
    lo: int = dataclasses.field(metadata=dict(static=True))
    hi: int = dataclasses.field(metadata=dict(static=True))
    base: int = dataclasses.field(metadata=dict(static=True))
    levels: int = dataclasses.field(metadata=dict(static=True))
    start: int = dataclasses.field(metadata=dict(static=True))
    refresh_every: int = dataclasses.field(metadata=dict(static=True))
    ttl: int = dataclasses.field(metadata=dict(static=True))
    pull_needy: bool = dataclasses.field(
        default=False, metadata=dict(static=True)
    )

    @property
    def base_idx(self) -> int:
        """Level index of the static baseline fanout (the shrink floor
        while any live message is still under target)."""
        return self.base - self.lo


def compile_control(
    *,
    target_ratio: float,
    fanout: int,
    lo: int | None = None,
    hi: int | None = None,
    refresh_every: int = 0,
    ttl: int = 0,
    sat_dup: float = 0.8,
    pull_knee: float = 0.0,
    pull_needy: bool | None = None,
) -> ControlSpec:
    """Compile a feedback-control policy (one spec serves every engine).

    ``fanout`` is the config's STATIC baseline ``m`` — it must lie inside
    ``[lo, hi]`` so the policy can always express the uncontrolled rate
    (and so ``lo == hi == fanout`` is the exact zero-adjustment spec).
    ``ttl`` is the streaming slot TTL when a stream rides the run (0:
    no stream — the per-slot lag signal compiles out). ``refresh_every``
    is the PeerSwap cadence in rounds (0: off). ``pull_needy`` gates the
    needy-pull saving (push_pull mode: a peer already holding every live
    message's bits does not issue its anti-entropy request — delivery-
    exact, only the request/answer billing moves); it defaults to ON
    exactly when the bounds are not pinned, so the zero-adjustment spec
    stays bit-identical to the uncontrolled run with no extra flags.
    Validates as a precondition: impossible policies are config errors
    before anything traces.
    """
    import jax.numpy as jnp
    import numpy as np

    if not (0.0 < target_ratio <= 1.0):
        raise ControlError(
            f"target_ratio {target_ratio} outside (0, 1] — it is the "
            "delivery-ratio the controller defends"
        )
    if not (0.0 < sat_dup <= 1.0):
        raise ControlError(f"sat_dup {sat_dup} outside (0, 1]")
    if not (0.0 <= pull_knee <= 1.0):
        raise ControlError(f"pull_knee {pull_knee} outside [0, 1]")
    if lo is None:
        lo = 1
    if hi is None:
        hi = max(2 * fanout, fanout)
    if lo < 1:
        raise ControlError(f"fanout bound lo={lo} must be >= 1")
    if hi < lo:
        raise ControlError(f"fanout bounds lo={lo} > hi={hi}")
    if not (lo <= fanout <= hi):
        raise ControlError(
            f"static fanout {fanout} outside the control bounds "
            f"[{lo}, {hi}] — the policy must be able to express the "
            "uncontrolled rate"
        )
    if refresh_every < 0:
        raise ControlError(f"refresh_every {refresh_every} must be >= 0")
    if ttl < 0:
        raise ControlError(f"ttl {ttl} must be >= 0")
    clean = np.arange(lo, hi + 1, dtype=np.int32)
    # the mix rule: anti-entropy pulls run at-or-below the baseline (the
    # saturated regime); the widened CLEAN levels are pure push. With
    # lo == hi == fanout every level keeps the pull half on — the
    # zero-adjustment identity.
    pull = clean <= fanout
    if hi > fanout:
        # the stress rung: widest fanout WITH anti-entropy, reachable only
        # by under-delivery widening past the clean-start level
        table = np.concatenate([clean, np.asarray([hi], dtype=np.int32)])
        pull = np.concatenate([pull, np.asarray([True])])
        start = len(clean) - 1
    else:
        table = clean
        start = len(clean) - 1
    return ControlSpec(
        fanout_table=jnp.asarray(table),
        pull_table=jnp.asarray(pull),
        target_ratio=jnp.asarray(target_ratio, dtype=jnp.float32),
        sat_dup=jnp.asarray(sat_dup, dtype=jnp.float32),
        pull_knee=jnp.asarray(pull_knee, dtype=jnp.float32),
        lo=int(lo),
        hi=int(hi),
        base=int(fanout),
        levels=int(len(table)),
        start=int(start),
        refresh_every=int(refresh_every),
        ttl=int(ttl),
        pull_needy=bool(
            (lo, hi) != (fanout, fanout) if pull_needy is None
            else pull_needy
        ),
    )
