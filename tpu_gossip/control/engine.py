"""In-round adaptive control: the feedback engine's on-device half.

Two hooks, both inside the jitted round and shared by all three local
engines and both mesh engines so the policy exists once and cannot
drift:

- :func:`control_round` runs at the TOP of the round: it resolves the
  state's level cursor (``SwarmState.control_lvl``) against the compiled
  :class:`~tpu_gossip.control.ControlSpec` into this round's
  :class:`RoundControl` — the traced effective fanout ``m_eff`` (a value
  in ``[lo, hi]``) and the traced pull gate. Dissemination consumes it:
  the exactly-k XLA path draws at the static width ``hi`` and masks
  columns past ``m_eff`` (zero-adjustment bounds make the mask all-true,
  so the draws and bits are the uncontrolled ones), and every
  Bernoulli-per-edge engine (staircase kernel, matching family, bucketed
  mesh) scales its activation law to ``m_eff/deg`` — same draw shapes,
  same keys, only the thresholds move, which is what keeps the
  local ↔ sharded bit-identity contract intact under control.
- :func:`apply_control` runs as the LAST stage of ``advance_round``: it
  reads the round's realized feedback — delivered vs duplicate bits
  (``incoming`` against the pre-round ``seen``), the fault head's
  realized loss ratio, and the streaming plane's per-slot ages — and
  moves the level cursor AIMD-style: **additive widen** (+1 level) when
  the observed delivery signal falls below ``target_ratio`` (loss above
  the target's tolerance, or a live stream slot past half its TTL still
  under target coverage), **multiplicative shrink** (level halves) when
  the duplicate rate saturates (``sat_dup``). It also runs the PeerSwap
  neighbor refresh: every ``refresh_every`` rounds each live re-wired
  peer swaps one uniformly-chosen fresh-edge slot for a new
  degree-preferential endpoint draw, releasing the degree credit of the
  edge it discards and granting it to the new one — the re-wiring
  plane's book-balance invariant is preserved exactly (test-pinned).

Every stochastic choice draws from ``fold_in(state.rng,
CONTROL_STREAM_SALT)`` at GLOBAL shape outside ``shard_map`` — a
derivation parallel to the protocol's 5-way split and the
fault/growth/traffic streams, overlapping none of them — so
``control=None`` (and a zero-adjustment spec) reproduces the
uncontrolled protocol trajectory bit for bit, and controlled runs stay
bit-identical local vs sharded across modes × scenarios × growth ×
stream (tests/sim/test_control.py pins the matrix). The feedback itself
is integer sums (order-independent under sharding), so the level
trajectory is bit-exact across engine layouts too.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_gossip.core.streams import CONTROL_STREAM_SALT

__all__ = [
    "CONTROL_STREAM_SALT",
    "RoundControl",
    "ControlTelemetry",
    "control_round",
    "apply_control",
]


class RoundControl(NamedTuple):
    """One round's resolved control decision (consumed by dissemination)."""

    m_eff: jax.Array  # i32 () — effective fanout this round
    pull_on: jax.Array  # bool () — run the pull half (push_pull mode)
    lvl: jax.Array  # i32 () — resolved level index into the tables
    width: int  # static — draw width for exactly-k paths (= spec.hi)
    # (N,) bool — peers still MISSING some live message's bits, or None
    # when the needy-pull gate is off (spec.pull_needy). A sated peer's
    # pull delivers nothing it lacks (every seen bit lives on a leased
    # slot — expiry clears columns globally), so its request is simply
    # not issued: delivery-exact, and the late-phase request flood
    # collapses to the stragglers who actually need it.
    needy: jax.Array | None


class ControlTelemetry(NamedTuple):
    """Per-round controller counters for RoundStats (all scalar int32)."""

    level: jax.Array  # level that drove THIS round's fanout
    fanout: jax.Array  # effective fanout this round
    duplicate: jax.Array  # delivered bits landing on already-seen slots
    refreshed: jax.Array  # PeerSwap slot swaps applied this round


def control_round(spec, state, want_needy: bool = False) -> RoundControl:
    """Resolve the state's cursor into this round's decision.

    ``want_needy`` (static — the caller passes ``cfg.mode ==
    "push_pull"``) computes the needy-pull row mask only when a pull half
    exists to consume it.

    The cursor packs ``level + levels * stress_bit``: the level indexes
    the bounded fanout/mix tables; the stress bit latches the previous
    round's under-delivery signal so a stressed run keeps its
    anti-entropy half regardless of level. The mix's third gate is
    LAG-FREE feedback read off the state itself: a pull succeeds for a
    given message with probability ≈ that message's current coverage, so
    the pull half switches on the round some live lease's coverage
    passes ``pull_knee`` (and back off once every live message covered —
    the post-coverage savings regime). A cursor of -1 (``init_swarm`` /
    pre-control checkpoints) starts at ``spec.start`` — the widest CLEAN
    level: the epidemic-growth regime, where extra fanout buys coverage
    speed for near-zero duplicate cost; the AIMD shrink walks the level
    down as duplicates saturate, and only the under-delivery widening
    path climbs past the start onto the stress rung. Cursors from a
    checkpoint saved under different bounds clip into the current table.
    """
    levels = spec.levels
    cursor = jnp.clip(state.control_lvl, 0, 2 * levels - 1)
    lvl = jnp.where(
        state.control_lvl < 0, spec.start, cursor % levels
    ).astype(jnp.int32)
    stress_bit = jnp.where(
        state.control_lvl < 0, False, cursor >= levels
    )
    # the knee gate, computed on THIS round's state (integer sums —
    # bit-exact across engine layouts): some live message is past the
    # coverage knee where pulls start succeeding, yet under target
    live = state.alive & ~state.declared_dead
    n_live = jnp.maximum(jnp.sum(live, dtype=jnp.int32), 1)
    slot_cov = (
        jnp.sum(state.seen & live[:, None], axis=0, dtype=jnp.int32)
        .astype(jnp.float32)
        / n_live.astype(jnp.float32)
    )
    knee_gate = jnp.any(
        (state.slot_lease >= 0)
        & (slot_cov < spec.target_ratio)
        & (slot_cov >= spec.pull_knee)
    )
    needy = None
    if spec.pull_needy and want_needy:
        needy = jnp.any(
            (state.slot_lease >= 0)[None, :] & ~state.seen, axis=1
        )
    return RoundControl(
        m_eff=spec.fanout_table[lvl],
        pull_on=spec.pull_table[lvl] | stress_bit | knee_gate,
        lvl=lvl,
        width=spec.hi,
        needy=needy,
    )


def apply_control(
    spec,
    rng: jax.Array,
    rnd: jax.Array,
    rc: RoundControl,
    *,
    incoming: jax.Array,
    seen_prev: jax.Array,
    seen: jax.Array,
    alive: jax.Array,
    declared_dead: jax.Array,
    exists: jax.Array,
    rewired: jax.Array,
    rewire_targets: jax.Array,
    degree_credit: jax.Array,
    row_ptr: jax.Array,
    col_idx: jax.Array,
    slot_lease: jax.Array,
    rewire_slots: int,
    fstats=None,
) -> tuple[jax.Array, jax.Array, jax.Array, ControlTelemetry]:
    """One AIMD level update + the PeerSwap refresh; returns
    ``(control_lvl, rewire_targets, degree_credit, telemetry)``.

    ``rng`` is the round's ROOT key (``state.rng``) — the control stream
    derives by ``fold_in`` and consumes nothing of the protocol's 5-way
    split or the other registered streams. Runs after the fused tail and
    the churn/growth/stream stages, so the feedback reads the round's
    FINAL liveness and lease tables and the swap acts on the post-churn,
    post-growth re-wiring plane. All feedback reductions are integer
    (order-independent), so the level trajectory is bit-exact across
    engine layouts; the refresh draws are made every controlled round at
    full ``(N,)`` shape and masked by the cadence (stream positions
    depend only on the round — the faults/growth/traffic convention), so
    cadence edits never shift later rounds' randomness.
    """
    levels = spec.levels

    # --- feedback: duplicate saturation -----------------------------------
    live = alive & ~declared_dead
    inc_live = incoming & live[:, None]
    total_inc = jnp.sum(inc_live, dtype=jnp.int32)
    duplicate = jnp.sum(inc_live & seen_prev, dtype=jnp.int32)
    dup_rate = duplicate.astype(jnp.float32) / jnp.maximum(
        total_inc, 1
    ).astype(jnp.float32)
    saturated = (total_inc > 0) & (dup_rate >= spec.sat_dup)

    # --- feedback: under-delivery -----------------------------------------
    # (a) the fault head's realized loss ratio eats into the delivery
    # budget: widen while the network drops more than the target tolerates
    under = jnp.zeros((), dtype=bool)
    if fstats is not None:
        dropped = fstats.msgs_dropped.astype(jnp.float32)
        landed = fstats.msgs_delivered.astype(jnp.float32)
        loss_ratio = dropped / jnp.maximum(dropped + landed, 1.0)
        under = under | (loss_ratio > (1.0 - spec.target_ratio))
    # (b) per-slot coverage: every live message (an occupied slot lease —
    # the single-epidemic seed and every streaming injection lease one)
    # still under the target's live coverage is an epidemic IN PROGRESS.
    # The global duplicate rate is dominated by the saturated incumbents,
    # so an unfloored shrink would starve exactly the messages the
    # contract judges — the shrink therefore FLOORS at the static
    # baseline while any live message is uncovered (and a fresh lease
    # snaps a narrowed controller back up to it); narrowing below base
    # is purely the POST-COVERAGE savings regime. Under a stream
    # (``ttl`` > 0), a lease past half its TTL still uncovered is a
    # message about to miss its window — widen.
    n_live = jnp.maximum(jnp.sum(live, dtype=jnp.int32), 1)
    slot_cov = (
        jnp.sum(seen & live[:, None], axis=0, dtype=jnp.int32).astype(
            jnp.float32
        )
        / n_live.astype(jnp.float32)
    )
    uncovered = (slot_lease >= 0) & (slot_cov < spec.target_ratio)
    floor = jnp.where(jnp.any(uncovered), spec.base_idx, 0).astype(jnp.int32)
    if spec.ttl > 0:
        age = rnd - slot_lease
        under = under | jnp.any(uncovered & (2 * age >= spec.ttl))

    # --- AIMD: additive widen beats multiplicative shrink -----------------
    lvl = jnp.where(
        under,
        jnp.minimum(rc.lvl + 1, levels - 1),
        jnp.where(saturated, rc.lvl // 2, rc.lvl),
    )
    lvl = jnp.clip(lvl, floor, levels - 1).astype(jnp.int32)
    # the stress bit: a round widened by under-delivery keeps its
    # anti-entropy half next round regardless of level (control_round's
    # knee gate handles the lag-free coverage half of the mix)
    cursor = (lvl + levels * under.astype(jnp.int32)).astype(jnp.int32)

    # --- PeerSwap neighbor refresh (rides the re-wiring plane) ------------
    refreshed = jnp.zeros((), dtype=jnp.int32)
    if spec.refresh_every > 0 and rewire_slots > 0 and col_idx.shape[0] > 1:
        n = exists.shape[0]
        k_ctl = jax.random.fold_in(rng, CONTROL_STREAM_SALT)
        k_slot, k_tgt = jax.random.split(k_ctl)
        due = (rnd % spec.refresh_every) == 0
        # one uniformly-chosen fresh-edge slot per row, one fresh
        # degree-preferential endpoint draw (the churn-join law: a uniform
        # index into the CSR endpoint list over the REAL edge span)
        slot = jax.random.randint(k_slot, (n,), 0, rewire_slots)
        e_real = jnp.maximum(row_ptr[-1], 1)
        draws = col_idx[jax.random.randint(k_tgt, (n,), 0, e_real)]
        rows_idx = jnp.arange(n, dtype=jnp.int32)
        self_draw = draws == rows_idx.astype(draws.dtype)
        new_tgt = jnp.where(
            exists[jnp.clip(draws, 0, n - 1)] & ~self_draw, draws, -1
        ).astype(rewire_targets.dtype)
        act = due & rewired & alive & exists
        old = rewire_targets[rows_idx, slot]
        # degree-credit bookkeeping: the discarded edge's credit is
        # RELEASED, the new edge's GRANTED — sum(credit) keeps tracking
        # the stored fresh targets of re-wired rows exactly (the fold
        # invariant rematerialize_rewired leans on)
        degree_credit = degree_credit.at[
            jnp.where(act & (old >= 0), old, n)
        ].add(-1, mode="drop")
        degree_credit = degree_credit.at[
            jnp.where(act & (new_tgt >= 0), new_tgt, n)
        ].add(1, mode="drop")
        rewire_targets = rewire_targets.at[rows_idx, slot].set(
            jnp.where(act, new_tgt, old)
        )
        refreshed = jnp.sum(act, dtype=jnp.int32)

    telem = ControlTelemetry(
        level=rc.lvl,
        fanout=rc.m_eff,
        duplicate=duplicate,
        refreshed=refreshed,
    )
    return cursor, rewire_targets, degree_credit, telem
