"""Adaptive protocol control: coverage-feedback fanout, push↔push-pull
mix, and the PeerSwap neighbor refresh (docs/adaptive_control.md).

``compile_control`` (control/plan.py) builds the jit-static
:class:`ControlSpec`; the round hooks live in control/engine.py and run
inside every engine's jitted round via ``sim.engine.advance_round``.
"""

from tpu_gossip.control.engine import (
    CONTROL_STREAM_SALT,
    ControlTelemetry,
    RoundControl,
    apply_control,
    control_round,
)
from tpu_gossip.control.plan import ControlError, ControlSpec, compile_control

__all__ = [
    "CONTROL_STREAM_SALT",
    "ControlError",
    "ControlSpec",
    "ControlTelemetry",
    "RoundControl",
    "compile_control",
    "control_round",
    "apply_control",
]
