"""Growth plans: capacity layout + admission schedule, compiled host-side.

A growing swarm runs at jit-static CAPACITY: the state is built with more
rows than live peers (the exists-mask machinery that already carries
churned and pad rows), and the growth engine flips reserved rows live in
per-round batches. Which rows are reserved — and in what admission order
— depends on the engine's slot layout, exactly like the scenario
compiler's node masks (faults/scenario.py):

- **flat** layouts (the local XLA/staircase engines, any host CSR padded
  by :func:`pad_graph_for_growth`): capacity rows are appended after the
  initial peers; admission order is row order.
- **sharded matching** layouts
  (``matching_powerlaw_graph_sharded(growth_rows=...)``): each shard
  block carries its own reserved rows; admission round-robins across
  shards so the mesh stays balanced while it grows.
- **bucketed mesh** layouts (``partition_graph`` over a padded CSR): the
  load-balance permutation scatters capacity rows over shards; admission
  order follows the ORIGINAL peer ids mapped through ``position``.

All three are expressed through one ``admit_rows`` array — the j-th
admitted peer's state row — so the engine half (growth/engine.py) is
layout-blind, and a local and a sharded run that share a layout admit
identical rows in identical order (the bit-identity contract's membership
extension).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

__all__ = [
    "GrowthError",
    "CompiledGrowth",
    "compile_growth",
    "pad_graph_for_growth",
    "matching_admit_rows",
]


class GrowthError(ValueError):
    """A growth config that cannot mean what it says (compile time)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CompiledGrowth:
    """An admission schedule compiled to device tables.

    ``admit_rows`` lists the state row of every growth slot in admission
    order, padded with an out-of-range drop row to ``total + max_batch``
    entries so the per-round dynamic slice never clamps; ``growable``
    marks exactly the rows ``admit_rows`` names, so
    ``sum(growable & exists)`` IS the number of peers admitted so far —
    the schedule cursor lives in the state, not in host bookkeeping, and
    a mid-growth checkpoint resumes exactly where it stopped. Static
    fields decide trace structure (batch shape, attachment width); traced
    tables carry the layout.
    """

    admit_rows: jax.Array  # int32 (total + max_batch,) — drop-row padded
    growable: jax.Array  # bool (N,) — rows the schedule may admit
    joins_per_round: int = dataclasses.field(metadata=dict(static=True))
    max_batch: int = dataclasses.field(metadata=dict(static=True))
    attach_m: int = dataclasses.field(metadata=dict(static=True))
    total: int = dataclasses.field(metadata=dict(static=True))
    gamma_d_min: int = dataclasses.field(default=4, metadata=dict(static=True))


def pad_graph_for_growth(graph, capacity: int):
    """Pad a host CSR Graph to ``capacity`` rows of growth headroom.

    Returns ``(padded_graph, exists)``: rows past ``graph.n`` are
    degree-0 (no static edges — an admitted peer's links are the fresh
    preferential-attachment edges the growth engine draws) and start
    non-existent. Works for the local engines directly and for
    ``partition_graph`` (the bucketed mesh), whose permutation spreads
    the degree-0 capacity rows across shards.
    """
    from tpu_gossip.core.topology import Graph

    n = graph.n
    if capacity < n:
        raise GrowthError(f"capacity {capacity} < initial peers {n}")
    if capacity == n:
        return graph, np.ones(n, dtype=bool)
    row_ptr = np.concatenate([
        graph.row_ptr,
        np.full(capacity - n, graph.row_ptr[-1], dtype=graph.row_ptr.dtype),
    ])
    exists = np.zeros(capacity, dtype=bool)
    exists[:n] = True
    return Graph(n=capacity, row_ptr=row_ptr, col_idx=graph.col_idx), exists


def matching_admit_rows(plan, total: int) -> np.ndarray:
    """Admission-ordered state rows for a matching layout built with
    ``matching_powerlaw_graph_sharded(..., growth_rows=...)``.

    Each shard block holds ``growth_rows`` reserved rows at block offsets
    ``[n_per, n_per + growth_rows)``; admission round-robins across
    shards so the mesh stays balanced while it grows. The SAME rows in
    the same order on the local and sharded runs of one plan — the
    bit-identity contract's membership half.
    """
    s, n_blk, n_per = plan.mesh_shards, plan.n_blk, plan.n_per
    per_shard = n_blk - n_per - 1  # reserved rows per block (pad row excluded)
    if total > per_shard * s:
        raise GrowthError(
            f"schedule admits {total} peers but the matching layout "
            f"reserves only {per_shard * s} growth rows — rebuild with "
            f"growth_rows >= {-(-total // s)}"
        )
    j = np.arange(total, dtype=np.int64)
    return (j % s) * n_blk + n_per + j // s


def compile_growth(
    *,
    n_initial: int,
    target: int,
    n_slots: int,
    joins_per_round: int,
    attach_m: int,
    admit_rows: np.ndarray | None = None,
    node_map=None,
    max_join_burst: int = 0,
    gamma_d_min: int = 4,
) -> "CompiledGrowth":
    """Compile an admission schedule for one engine's slot layout.

    ``target - n_initial`` peers will be admitted. ``admit_rows``
    (admission-ordered state rows) defaults to the flat layout
    ``[n_initial, target)``; ``node_map`` (an id→row callable, the same
    hook the scenario compiler takes) maps that default through an
    engine's permutation instead. ``max_join_burst`` sizes the static
    per-round batch for the largest ``join_burst`` any scenario phase can
    add on top of ``joins_per_round``. Validates as a precondition —
    impossible schedules are config errors before anything traces.
    """
    import jax.numpy as jnp

    total = int(target) - int(n_initial)
    if total < 0:
        raise GrowthError(
            f"growth target {target} below initial peers {n_initial}"
        )
    if joins_per_round < 0 or max_join_burst < 0:
        raise GrowthError("joins_per_round and join bursts must be >= 0")
    if total > 0 and joins_per_round + max_join_burst <= 0:
        raise GrowthError(
            f"{total} peers to admit but joins_per_round=0 and no "
            "join_burst phase — the swarm would never grow"
        )
    if attach_m <= 0:
        raise GrowthError(f"attach_m={attach_m} must be positive")
    if attach_m >= max(n_initial, 1):
        raise GrowthError(
            f"attach_m={attach_m} needs at least that many initial peers "
            f"to attach to (got {n_initial})"
        )
    if admit_rows is None:
        admit_rows = np.arange(n_initial, target, dtype=np.int64)
        if node_map is not None and total:
            admit_rows = np.asarray(node_map(admit_rows))
    admit_rows = np.asarray(admit_rows, dtype=np.int64)
    if admit_rows.shape != (total,):
        raise GrowthError(
            f"admit_rows has {admit_rows.shape} entries; the schedule "
            f"admits {total}"
        )
    if total and (admit_rows.min() < 0 or admit_rows.max() >= n_slots):
        raise GrowthError(
            f"admit_rows outside the state's [0, {n_slots}) row space"
        )
    if len(np.unique(admit_rows)) != total:
        raise GrowthError("admit_rows admits some row twice")
    max_batch = max(joins_per_round + max_join_burst, 1)
    growable = np.zeros(n_slots, dtype=bool)
    growable[admit_rows] = True
    padded = np.full(total + max_batch, n_slots, dtype=np.int32)  # drop row
    padded[:total] = admit_rows
    return CompiledGrowth(
        admit_rows=jnp.asarray(padded),
        growable=jnp.asarray(growable),
        joins_per_round=int(joins_per_round),
        max_batch=int(max_batch),
        attach_m=int(attach_m),
        total=int(total),
        gamma_d_min=int(gamma_d_min),
    )
