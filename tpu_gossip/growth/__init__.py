"""Growth engine: in-round preferential-attachment joins.

The reference's defining behavior — seeds bootstrapping new peers into a
power-law topology by degree-preferential subset handout (Seed.py
``get_peer_subset`` + demonstrate_powerlaw.py) — as a vectorized
membership plane inside the jitted round: swarms GROW while gossiping, at
jit-static capacity, bit-identically on the local and sharded engines.
See docs/growth_engine.md for the admission semantics, capacity model,
PRNG stream layout, and determinism contract.
"""

from tpu_gossip.growth.engine import (
    GROWTH_STREAM_SALT,
    apply_growth,
    hill_gamma_device,
    realized_degrees,
)
from tpu_gossip.growth.plan import (
    CompiledGrowth,
    GrowthError,
    compile_growth,
    matching_admit_rows,
    pad_graph_for_growth,
)

__all__ = [
    "GROWTH_STREAM_SALT",
    "CompiledGrowth",
    "GrowthError",
    "apply_growth",
    "compile_growth",
    "hill_gamma_device",
    "matching_admit_rows",
    "pad_graph_for_growth",
    "realized_degrees",
]
