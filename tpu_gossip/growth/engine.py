"""In-round admission: the growth engine's on-device half.

One call to :func:`apply_growth` admits one round's join batch INSIDE the
jitted round, as the row-level stage of ``sim.engine.advance_round`` —
shared by all three delivery engines, so the membership plane exists
once and cannot drift between them:

- the batch size is ``joins_per_round`` plus any active scenario phase's
  ``join_burst`` (faults/scenario.py), clipped to the remaining schedule;
- each joiner draws ``attach_m`` DISTINCT target neighbors by
  preferential attachment over the current REALIZED degree vector
  (static CSR degree of existing rows + outstanding growth-edge credit)
  via Gumbel-top-k over masked log-degrees: ``argtop_k(log deg + G)``
  samples k items without replacement with probability proportional to
  degree — the exponential-race formulation of the reference's intended
  ``powerlaw_connect`` semantics, with no data-dependent shapes;
- the draw comes from ``fold_in(state.rng, GROWTH_STREAM_SALT)`` at
  GLOBAL shape — a derivation parallel to the protocol's 5-way split and
  the fault stream's ``FAULT_STREAM_SALT``, never overlapping either —
  so the local ↔ sharded bit-identity contract extends to growing swarms
  (every growth op is elementwise/scatter at global shape outside
  ``shard_map``; XLA's SPMD partitioner inserts the collectives), and a
  zero-join growth config reproduces the fixed-n trajectory bit for bit;
- the admitted rows flip ``exists``/``alive`` live, record their
  bootstrap in the registry plane (``join_round``, ``admitted_by`` = the
  top-scored attachment target — the hub that bootstrapped the peer,
  the vectorized twin of the reference seed's subset handout), and their
  fresh edges ride the EXISTING churn re-wiring plane
  (``rewired``/``rewire_targets``): delivery over fresh edges, the
  bidirectional reverse push, the compact O(cap) side paths, and
  ``rematerialize_rewired``'s CSR fold all apply to growth edges
  unchanged, on every engine.

Batch-admission approximation (documented generator semantics): joiners
in one round's batch attach to the pre-batch membership — two same-round
joiners never pick each other, exactly like the reference's registration
window (a registering peer's subset comes from the seed's CURRENT
registry, Seed.py:127-129).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_gossip.core.state import saturate_round
from tpu_gossip.core.topology import hill_gamma

__all__ = [
    "GROWTH_STREAM_SALT",
    "realized_degrees",
    "hill_gamma_device",
    "apply_growth",
]

# folds the round's root key (state.rng) into the growth stream — a
# derivation parallel to the protocol's 5-way split and the fault
# stream's FAULT_STREAM_SALT, overlapping neither. The value lives in the
# canonical stream registry (core/streams.py, where uniqueness is
# asserted at import); re-exported here for compatibility.
from tpu_gossip.core.streams import GROWTH_STREAM_SALT  # noqa: E402


def realized_degrees(
    row_ptr: jax.Array,
    exists: jax.Array,
    rewired: jax.Array,
    rewire_targets: jax.Array,
    degree_credit: jax.Array,
) -> jax.Array:
    """The degree vector a preferential-attachment draw weighs.

    A row's OUT side is read off the live tables, never a second book
    that could go stale: a re-wired row (growth joiner or churn rejoiner
    — its static CSR row is stale) counts its valid fresh targets;
    everyone else counts their CSR degree. The IN side of unfolded fresh
    edges is ``SwarmState.degree_credit`` (+1 per fresh edge pointing at
    the row, maintained by admission and by the churn re-wiring
    overwrite; ``rematerialize_rewired`` zeroes it as it folds the edges
    into the CSR). Exact for every re-wired row; a non-rewired row can
    over-count by its stale CSR edges into re-wired rows until the fold
    drops them — the same stale-edge class the delivery masks handle.
    """
    base = row_ptr[1:] - row_ptr[:-1]
    fresh = jnp.sum(rewire_targets >= 0, axis=1, dtype=jnp.int32)
    own = jnp.where(rewired, fresh, base.astype(jnp.int32))
    return jnp.where(exists, own, 0) + degree_credit


def hill_gamma_device(
    deg: jax.Array, live: jax.Array, d_min: int
) -> jax.Array:
    """Running γ-MLE over the live degree vector (the degree-evolution
    track): the SAME Hill/CSN estimator as
    ``core.topology.fit_powerlaw_gamma`` (shared ``hill_gamma``
    arithmetic), computed as two masked reductions so it rides the round
    on device. Returns 0.0 when the tail is too thin to estimate (< 10
    samples — the host fitter raises there instead).

    Determinism note: this is the ONE float reduction in the growth
    plane, and XLA brackets a sharded sum per shard — so across engine
    layouts the track agrees to float32 reduction tolerance (observed
    1 ULP), while the state trajectory and every integer stat stay
    bit-exact. Tests pin the state bitwise and this track to allclose.
    """
    tail = live & (deg >= d_min)
    k = jnp.sum(tail, dtype=jnp.int32)
    logs = jnp.where(
        tail,
        jnp.log(jnp.maximum(deg, 1).astype(jnp.float32) / (d_min - 0.5)),
        0.0,
    )
    s = jnp.sum(logs, dtype=jnp.float32)
    return jnp.where(
        (k >= 10) & (s > 0), hill_gamma(k, s), 0.0
    ).astype(jnp.float32)


def apply_growth(
    growth,
    rng: jax.Array,
    rnd: jax.Array,
    join_burst: jax.Array,
    *,
    row_ptr: jax.Array,
    exists: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    last_hb: jax.Array,
    declared_dead: jax.Array,
    rewired: jax.Array,
    rewire_targets: jax.Array,
    join_round: jax.Array,
    admitted_by: jax.Array,
    degree_credit: jax.Array,
) -> dict:
    """Admit one round's join batch; returns the updated row-level fields.

    ``rng`` is the round's ROOT key (``state.rng``) — the growth stream
    derives from it by ``fold_in`` and consumes nothing of the protocol's
    5-way split. ``join_burst`` is the active scenario phase's extra
    admissions (0 without one). All shapes are static
    (``growth.max_batch`` rows drawn every round regardless of the
    traced take count — stream positions depend only on the round, so
    schedule edits never shift later rounds' randomness), and a round
    with nothing left to admit is a masked no-op.
    """
    if growth.attach_m > rewire_targets.shape[1]:
        raise ValueError(
            f"growth.attach_m={growth.attach_m} exceeds the state's "
            f"rewire_targets width {rewire_targets.shape[1]} — growth "
            "edges ride the re-wiring plane; build the config with "
            f"rewire_slots >= {growth.attach_m}"
        )
    n = exists.shape[0]
    jb, m = growth.max_batch, growth.attach_m

    # schedule cursor: how many the state says are already admitted
    n_adm = jnp.sum(growth.growable & exists, dtype=jnp.int32)
    quota = growth.joins_per_round + join_burst.astype(jnp.int32)
    take = jnp.clip(jnp.minimum(quota, growth.total - n_adm), 0, jb)
    rows = jax.lax.dynamic_slice(growth.admit_rows, (n_adm,), (jb,))
    batch_live = jnp.arange(jb) < take

    # Gumbel-top-k preferential attachment over the realized degrees of
    # CURRENT members (this batch's rows still have exists=False, so
    # same-round joiners are never candidates, nor are pads or capacity)
    deg = realized_degrees(row_ptr, exists, rewired, rewire_targets,
                           degree_credit)
    attach_ok = exists & alive & ~declared_dead & (deg > 0)
    log_deg = jnp.where(
        attach_ok, jnp.log(jnp.maximum(deg, 1).astype(jnp.float32)), -jnp.inf
    )
    k_grow = jax.random.fold_in(rng, GROWTH_STREAM_SALT)
    gumbel = jax.random.gumbel(k_grow, (jb, n), dtype=jnp.float32)
    scores, targets = jax.lax.top_k(log_deg[None, :] + gumbel, m)  # (jb, m)
    t_valid = batch_live[:, None] & jnp.isfinite(scores)
    targets = targets.astype(jnp.int32)
    # the admitting seed is the TOP-scored target (column 0), extracted
    # by a full-width masked reduction rather than targets[:, 0]: XLA's
    # top-k simplifier rewrites a slice-of-top_k into a variadic argmax
    # reduce whose scalar CPU lowering is ~40x the whole top_k (measured
    # 757 ms vs 18 ms at (128, 32k)) — and guarding the slice with an
    # optimization_barrier instead crashes the CPU TopkDecomposer (it
    # casts every top_k user to get-tuple-element)
    col0 = (jnp.arange(m) == 0)[None, :]
    seed_id = jnp.sum(jnp.where(col0, targets, 0), axis=1)
    seed_ok = jnp.sum(jnp.where(col0 & t_valid, 1, 0), axis=1) > 0

    # registry + liveness flips, scattered at the batch rows (row `n` is
    # the drop row for the dead tail of the batch)
    sel = jnp.where(batch_live, rows, n)
    exists = exists.at[sel].set(True, mode="drop")
    alive = alive.at[sel].set(True, mode="drop")
    silent = silent.at[sel].set(False, mode="drop")
    declared_dead = declared_dead.at[sel].set(False, mode="drop")
    last_hb = last_hb.at[sel].set(
        saturate_round(rnd, last_hb.dtype), mode="drop"
    )
    # join_round is the narrow (int16) registry plane — scatter the round
    # cursor at the plane's declared width, SATURATED at ROUND_CAP: a
    # campaign past the cap records "joined at the cap" (late but valid)
    # instead of wrapping into the -1 never-joined sentinel
    join_round = join_round.at[sel].set(
        saturate_round(rnd, join_round.dtype), mode="drop"
    )
    admitted_by = admitted_by.at[sel].set(
        jnp.where(seed_ok, seed_id, -1), mode="drop"
    )

    # fresh edges onto the re-wiring plane: the joiner's traffic rides
    # fresh_rewire_traffic / reverse_fresh_push exactly like a churn
    # rejoiner's, and rematerialize_rewired folds the edges into the CSR
    width = rewire_targets.shape[1]
    fresh_tg = jnp.full((jb, width), -1, dtype=rewire_targets.dtype)
    fresh_tg = fresh_tg.at[:, :m].set(jnp.where(t_valid, targets, -1))
    rewired = rewired.at[sel].set(True, mode="drop")
    rewire_targets = rewire_targets.at[sel].set(fresh_tg, mode="drop")

    # degree credit: +1 at each target — the IN side of the fresh edges.
    # The joiner's OWN side is read off its rewire_targets by
    # realized_degrees (no second book), so the realized degree vector
    # sees both endpoints of every growth edge until the CSR fold
    # materializes them and zeroes the credit
    flat_t = jnp.where(t_valid, targets, n).reshape(-1)
    degree_credit = degree_credit.at[flat_t].add(1, mode="drop")

    return dict(
        exists=exists,
        alive=alive,
        silent=silent,
        last_hb=last_hb,
        declared_dead=declared_dead,
        rewired=rewired,
        rewire_targets=rewire_targets,
        join_round=join_round,
        admitted_by=admitted_by,
        degree_credit=degree_credit,
    )
