"""Chaos scenario engine: deterministic, time-phased fault injection.

The robustness pillar next to the perf and correctness-tooling work: a
declarative schedule (TOML/dict) of message loss, delivery delay,
partitions, blackouts, and churn bursts compiles to jit-friendly device
tables (:mod:`~tpu_gossip.faults.scenario`) that every engine — local,
bucketed mesh, matching mesh — applies identically from a dedicated PRNG
stream (:mod:`~tpu_gossip.faults.inject`), extending the local↔sharded
bit-identity contract to every scenario. See docs/fault_model.md.
"""

from tpu_gossip.faults.inject import (
    CompiledScenario,
    FaultTelemetry,
    RoundFaults,
    drain_held,
    faulted_dissemination,
    scenario_dissemination,
)
from tpu_gossip.faults.scenario import (
    FaultPhase,
    NodeSet,
    ScenarioError,
    ScenarioSpec,
    compile_scenario,
    parse_scenario,
    scenario_from_dict,
)

__all__ = [
    "CompiledScenario",
    "FaultTelemetry",
    "RoundFaults",
    "drain_held",
    "faulted_dissemination",
    "scenario_dissemination",
    "FaultPhase",
    "NodeSet",
    "ScenarioError",
    "ScenarioSpec",
    "compile_scenario",
    "parse_scenario",
    "scenario_from_dict",
]
