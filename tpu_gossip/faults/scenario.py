"""Declarative fault scenarios: parse, validate, compile to device tables.

A scenario is a time-phased fault schedule — named phases over disjoint
round ranges, each enabling some mix of message loss, delivery delay, a
two-group partition, node blackouts, and churn bursts. It is authored as
TOML (or an equivalent dict for tests/library use)::

    [scenario]
    name = "split-brain"

    [[phase]]
    name  = "partition"
    start = 5          # phase covers rounds 6..20 (0-based offsets 5..19)
    end   = 20
    partition = "half" # group B = upper half of peer ids

    [[phase]]
    name  = "lossy-heal"
    start = 20
    end   = 30
    loss  = 0.3

Phase ``start``/``end`` are 0-based round OFFSETS from the start of the
run, half-open: a phase ``[s, e)`` governs the rounds that take
``state.round`` from ``s`` to ``e``. Phases must be disjoint (overlap is
an ambiguity, rejected at validation) and must fit inside the run's
horizon (``run_sim`` rejects a schedule naming rounds past ``--rounds`` /
``--max-rounds`` before anything compiles). Rounds no phase claims — and
every round past the schedule — are quiescent: no faults, held
deliveries drain.

Node sets (for ``partition`` / ``blackout`` / ``churn_nodes``) are
declared over REAL peer ids ``[0, n_peers)`` and resolved to state rows
at compile time through the engine's layout (``node_map`` — the bucketed
mesh's load-balance permutation, the sharded matching row mapping), so
one scenario file runs identically on every engine. Forms:

- ``"all"`` / ``"half"`` — everyone / the upper half of peer ids
- ``{ids = [3, 17, 40]}`` — explicit peers
- ``{frac = 0.25, seed = 7}`` — a random fraction (deterministic in seed)
- ``{span = [0.5, 0.75]}`` — a contiguous id range by fraction (a "rack")
- ``{shards = [1, 2]}`` — whole mesh shards, resolved in SLOT space via
  ``shard_ranges`` (sharded runs only — local runs reject it)

This container runs Python 3.10 (no stdlib ``tomllib``), so a reader for
the restricted subset scenarios use lives here — ``[scenario]``,
``[[phase]]``, scalar values, arrays, and one-level inline tables. Not a
general TOML parser; round-trip is covered by tests/sim/test_faults.py.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from tpu_gossip.faults.inject import CompiledScenario

__all__ = [
    "ScenarioError",
    "NodeSet",
    "FaultPhase",
    "ScenarioSpec",
    "parse_scenario",
    "scenario_from_dict",
    "compile_scenario",
]


class ScenarioError(ValueError):
    """A scenario file that cannot mean what it says (parse/validate time)."""


# --------------------------------------------------------------- the spec
@dataclasses.dataclass(frozen=True)
class NodeSet:
    """A declarative peer set, resolved to a row mask at compile time."""

    kind: str  # "all" | "half" | "ids" | "frac" | "span" | "shards"
    ids: tuple[int, ...] = ()
    frac: float = 0.0
    seed: int = 0
    span: tuple[float, float] = (0.0, 0.0)
    shards: tuple[int, ...] = ()

    def covers_all(self, n_peers: int, n_shards: int | None) -> bool:
        """True when the set provably selects every peer — in any spelling
        (``"all"``, ``frac=1.0``, a full span, an exhaustive id list, every
        shard), so degenerate partitions can't sneak past validation."""
        if self.kind == "all":
            return True
        if self.kind == "frac":
            return int(round(self.frac * n_peers)) >= n_peers
        if self.kind == "span":
            lo, hi = self.span
            return int(lo * n_peers) == 0 and int(hi * n_peers) >= n_peers
        if self.kind == "ids":
            return len(set(self.ids)) >= n_peers
        if self.kind == "shards" and n_shards is not None:
            return set(self.shards) >= set(range(n_shards))
        return False

    def validate(self, n_peers: int, n_shards: int | None, where: str) -> None:
        if self.kind not in ("all", "half", "ids", "frac", "span", "shards"):
            raise ScenarioError(f"{where}: unknown node-set kind {self.kind!r}")
        if self.kind == "ids":
            bad = [i for i in self.ids if not 0 <= i < n_peers]
            if bad:
                raise ScenarioError(
                    f"{where}: peer ids {bad} outside [0, {n_peers})"
                )
        if self.kind == "frac" and not 0.0 <= self.frac <= 1.0:
            raise ScenarioError(f"{where}: frac {self.frac} outside [0, 1]")
        if self.kind == "span":
            lo, hi = self.span
            if not (0.0 <= lo < hi <= 1.0):
                raise ScenarioError(
                    f"{where}: span {self.span} must satisfy 0 <= lo < hi <= 1"
                )
        if self.kind == "shards":
            if n_shards is None:
                raise ScenarioError(
                    f"{where}: names mesh shards, but this run is not "
                    "sharded (use --shard, or a frac/span/ids set)"
                )
            bad = [s for s in self.shards if not 0 <= s < n_shards]
            if bad:
                raise ScenarioError(
                    f"{where}: shard ids {bad} outside [0, {n_shards})"
                )

    def resolve(
        self,
        n_peers: int,
        n_slots: int,
        node_map,
        shard_ranges: list[tuple[int, int]] | None,
    ) -> np.ndarray:
        """(n_slots,) bool row mask for this set under the engine layout."""
        mask = np.zeros(n_slots, dtype=bool)
        if self.kind == "shards":
            for s in self.shards:
                lo, hi = shard_ranges[s]
                mask[lo:hi] = True
            return mask
        if self.kind == "all":
            ids = np.arange(n_peers)
        elif self.kind == "half":
            ids = np.arange(n_peers // 2, n_peers)
        elif self.kind == "ids":
            ids = np.asarray(self.ids, dtype=np.int64)
        elif self.kind == "frac":
            rng = np.random.default_rng(self.seed)
            k = int(round(self.frac * n_peers))
            ids = rng.choice(n_peers, size=min(k, n_peers), replace=False)
        else:  # span
            lo, hi = self.span
            ids = np.arange(int(lo * n_peers), int(hi * n_peers))
        if node_map is not None and len(ids):
            ids = np.asarray(node_map(np.asarray(ids, dtype=np.int64)))
        mask[ids] = True
        return mask


ALL_NODES = NodeSet(kind="all")


@dataclasses.dataclass(frozen=True)
class FaultPhase:
    """One schedule entry: a round range and the faults it enables."""

    name: str
    start: int  # 0-based round offset, inclusive
    end: int  # exclusive
    loss: float = 0.0
    delay: float = 0.0
    churn_leave: float = 0.0
    churn_join: float = 0.0
    churn_nodes: NodeSet = ALL_NODES
    partition: NodeSet | None = None  # group B of the split
    blackout: NodeSet | None = None
    # admission wave (growth/): extra joins per round ON TOP of the
    # active growth schedule's rate — composes churn storms with growth
    # bursts. Requires a growing run (run_sim rejects it without --grow).
    join_burst: int = 0
    # Byzantine adversaries (docs/adversarial_model.md) — require the
    # quorum-defense planes (run_sim rejects them without --quorum-k):
    # ``accusers`` emit one false dead-verdict per round each against a
    # uniformly sampled live victim (the reference's single-report purge
    # vulnerability, Seed.py:358-406); ``forgers`` emit ``forge_fanout``
    # forged heartbeats per round each on behalf of sampled peers,
    # stalling detection of the genuinely dead; ``floods`` replay each
    # flooder's full seen bitmap at ``flood_fanout`` sampled targets per
    # round — duplicate pressure on the dedup/Bloom plane (and on the
    # AIMD controller's duplicate-saturation feedback).
    accusers: NodeSet | None = None
    forgers: NodeSet | None = None
    floods: NodeSet | None = None
    forge_fanout: int = 2
    flood_fanout: int = 2


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """A parsed, not-yet-compiled scenario."""

    name: str
    phases: tuple[FaultPhase, ...]

    @property
    def last_round(self) -> int:
        return max((p.end for p in self.phases), default=0)

    @property
    def max_join_burst(self) -> int:
        """Largest per-round admission wave any phase adds — sizes the
        growth engine's static batch shape (growth/plan.compile_growth)."""
        return max((p.join_burst for p in self.phases), default=0)

    @property
    def uses_join_burst(self) -> bool:
        return any(p.join_burst for p in self.phases)

    @property
    def uses_adversaries(self) -> bool:
        """True when any phase fields Byzantine adversaries — such
        scenarios need the quorum-defense planes compiled in (run_sim
        rejects them without ``--quorum-k``)."""
        return any(
            p.accusers is not None or p.forgers is not None
            or p.floods is not None
            for p in self.phases
        )

    @property
    def max_forge_fanout(self) -> int:
        """Static draw width for the forgery scatter (0 = no forgers)."""
        return max(
            (p.forge_fanout for p in self.phases if p.forgers is not None),
            default=0,
        )

    @property
    def max_flood_fanout(self) -> int:
        """Static draw width for the flood scatter (0 = no floods)."""
        return max(
            (p.flood_fanout for p in self.phases if p.floods is not None),
            default=0,
        )

    @property
    def uses_node_sets(self) -> bool:
        """True when any phase scopes a fault to a proper peer subset —
        such masks are fixed in the initial slot layout and do NOT survive
        an epoch re-partition (``--shard --remat-every``)."""
        return any(
            p.partition is not None
            or p.blackout is not None
            or p.accusers is not None
            or p.forgers is not None
            or p.floods is not None
            or (p.churn_nodes.kind != "all" and (p.churn_leave or p.churn_join))
            for p in self.phases
        )

    def validate(
        self,
        *,
        total_rounds: int,
        n_peers: int,
        n_shards: int | None = None,
    ) -> None:
        """Reject impossible schedules BEFORE anything runs: phases past
        the horizon, overlapping phases, out-of-range probabilities or
        node sets, empty/total partitions."""
        if not self.phases:
            raise ScenarioError("scenario has no phases")
        for p in self.phases:
            w = f"phase {p.name!r}"
            if p.start < 0 or p.end <= p.start:
                raise ScenarioError(
                    f"{w}: round range [{p.start}, {p.end}) is empty or "
                    "negative"
                )
            if p.end > total_rounds:
                raise ScenarioError(
                    f"{w}: ends at round {p.end}, beyond the run's horizon "
                    f"of {total_rounds} rounds — a schedule the run can "
                    "never reach is a config error, not a no-op"
                )
            for field in ("loss", "delay", "churn_leave", "churn_join"):
                v = getattr(p, field)
                if not 0.0 <= v <= 1.0:
                    raise ScenarioError(
                        f"{w}: {field}={v} outside [0, 1]"
                    )
            if p.join_burst < 0:
                raise ScenarioError(
                    f"{w}: join_burst={p.join_burst} must be >= 0"
                )
            p.churn_nodes.validate(n_peers, n_shards, f"{w}.churn_nodes")
            if p.partition is not None:
                p.partition.validate(n_peers, n_shards, f"{w}.partition")
                if p.partition.covers_all(n_peers, n_shards):
                    raise ScenarioError(
                        f"{w}: partition group B covers every peer — group "
                        "A would be empty and the 'partition' a silent "
                        "no-op (use blackout to cut everyone off)"
                    )
            if p.blackout is not None:
                p.blackout.validate(n_peers, n_shards, f"{w}.blackout")
            for adv in ("accusers", "forgers", "floods"):
                ns = getattr(p, adv)
                if ns is None:
                    continue
                ns.validate(n_peers, n_shards, f"{w}.{adv}")
                if ns.covers_all(n_peers, n_shards):
                    raise ScenarioError(
                        f"{w}: {adv} covers every peer — an all-adversary "
                        "swarm has no honest protocol left to attack "
                        "(scope the set below the full membership)"
                    )
            if p.forgers is not None and p.forge_fanout < 1:
                raise ScenarioError(
                    f"{w}: forge_fanout={p.forge_fanout} must be >= 1 when "
                    "the phase fields forgers"
                )
            if p.floods is not None and p.flood_fanout < 1:
                raise ScenarioError(
                    f"{w}: flood_fanout={p.flood_fanout} must be >= 1 when "
                    "the phase fields floods"
                )
        ordered = sorted(self.phases, key=lambda p: (p.start, p.end))
        for a, b in zip(ordered, ordered[1:]):
            if b.start < a.end:
                raise ScenarioError(
                    f"phases {a.name!r} [{a.start}, {a.end}) and {b.name!r} "
                    f"[{b.start}, {b.end}) overlap — which phase governs "
                    f"round {b.start + 1} is ambiguous"
                )


# ------------------------------------------------------------- the parser
def _parse_value(s: str):
    s = s.strip()
    if s.startswith("{") and s.endswith("}"):
        body = s[1:-1].strip()
        out = {}
        for part in _split_top(body, ","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            if not _:
                raise ScenarioError(f"bad inline-table entry {part!r}")
            out[k.strip()] = _parse_value(v)
        return out
    if s.startswith("[") and s.endswith("]"):
        body = s[1:-1].strip()
        return [_parse_value(p) for p in _split_top(body, ",") if p.strip()]
    if len(s) >= 2 and s[0] == s[-1] and s[0] in ("'", '"'):
        return s[1:-1]
    if s in ("true", "false"):
        return s == "true"
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        raise ScenarioError(f"cannot parse value {s!r}") from None


def _split_top(s: str, sep: str) -> list[str]:
    """Split on ``sep`` outside brackets/braces/quotes (one level deep)."""
    parts, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
        elif ch in "[{":
            depth += 1
        elif ch in "]}":
            depth -= 1
        elif ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    parts.append("".join(cur))
    return parts


def _strip_comment(raw: str) -> str:
    quote = None
    for i, ch in enumerate(raw):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return raw[:i]
    return raw


def _toml_tables(text: str) -> tuple[dict, list[dict]]:
    """(scenario_table, phase_tables) from the scenario TOML subset."""
    scenario: dict = {}
    phases: list[dict] = []
    cur: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line == "[scenario]":
            cur = scenario
        elif line == "[[phase]]":
            cur = {}
            phases.append(cur)
        elif line.startswith("["):
            raise ScenarioError(
                f"line {lineno}: unknown table {line!r} (scenario files "
                "hold one [scenario] table and [[phase]] entries)"
            )
        else:
            key, eq, value = line.partition("=")
            if not eq:
                raise ScenarioError(f"line {lineno}: expected key = value")
            if cur is None:
                raise ScenarioError(
                    f"line {lineno}: key outside any table"
                )
            cur[key.strip()] = _parse_value(value)
    return scenario, phases


def _node_set(v, where: str) -> NodeSet:
    if isinstance(v, NodeSet):
        return v
    if isinstance(v, str):
        if v in ("all", "half"):
            return NodeSet(kind=v)
        raise ScenarioError(f"{where}: unknown node-set keyword {v!r}")
    if not isinstance(v, dict):
        raise ScenarioError(f"{where}: expected a node-set table, got {v!r}")
    keys = set(v) - {"seed"}
    if keys == {"ids"}:
        return NodeSet(kind="ids", ids=tuple(int(i) for i in v["ids"]))
    if keys == {"frac"}:
        return NodeSet(
            kind="frac", frac=float(v["frac"]), seed=int(v.get("seed", 0))
        )
    if keys == {"span"}:
        lo, hi = v["span"]
        return NodeSet(kind="span", span=(float(lo), float(hi)))
    if keys == {"shards"}:
        return NodeSet(kind="shards", shards=tuple(int(s) for s in v["shards"]))
    raise ScenarioError(
        f"{where}: node set needs exactly one of ids/frac/span/shards, "
        f"got keys {sorted(v)}"
    )


_PHASE_KEYS = {
    "name", "start", "end", "loss", "delay", "churn_leave", "churn_join",
    "churn_nodes", "partition", "blackout", "join_burst",
    "accusers", "forgers", "floods", "forge_fanout", "flood_fanout",
}


def scenario_from_dict(d: dict) -> ScenarioSpec:
    """Build a spec from a plain dict (the TOML surface, for library use).

    ``{"name": ..., "phases": [{...}, ...]}`` with phase dicts carrying
    the TOML keys."""
    phases = []
    for i, p in enumerate(d.get("phases", ())):
        unknown = set(p) - _PHASE_KEYS
        if unknown:
            raise ScenarioError(
                f"phase {i}: unknown keys {sorted(unknown)} (known: "
                f"{sorted(_PHASE_KEYS)})"
            )
        if "start" not in p or "end" not in p:
            raise ScenarioError(f"phase {i}: start and end are required")
        name = str(p.get("name", f"phase{i}"))
        phases.append(
            FaultPhase(
                name=name,
                start=int(p["start"]),
                end=int(p["end"]),
                loss=float(p.get("loss", 0.0)),
                delay=float(p.get("delay", 0.0)),
                churn_leave=float(p.get("churn_leave", 0.0)),
                churn_join=float(p.get("churn_join", 0.0)),
                churn_nodes=_node_set(
                    p.get("churn_nodes", ALL_NODES), f"phase {name!r}.churn_nodes"
                ),
                partition=(
                    None
                    if p.get("partition") is None
                    else _node_set(p["partition"], f"phase {name!r}.partition")
                ),
                blackout=(
                    None
                    if p.get("blackout") is None
                    else _node_set(p["blackout"], f"phase {name!r}.blackout")
                ),
                join_burst=int(p.get("join_burst", 0)),
                accusers=(
                    None if p.get("accusers") is None
                    else _node_set(p["accusers"], f"phase {name!r}.accusers")
                ),
                forgers=(
                    None if p.get("forgers") is None
                    else _node_set(p["forgers"], f"phase {name!r}.forgers")
                ),
                floods=(
                    None if p.get("floods") is None
                    else _node_set(p["floods"], f"phase {name!r}.floods")
                ),
                forge_fanout=int(p.get("forge_fanout", 2)),
                flood_fanout=int(p.get("flood_fanout", 2)),
            )
        )
    return ScenarioSpec(
        name=str(d.get("name", "scenario")), phases=tuple(phases)
    )


def parse_scenario(source: str | Path) -> ScenarioSpec:
    """Parse a scenario TOML file (or TOML text containing a newline)."""
    text = (
        str(source)
        if isinstance(source, str) and "\n" in source
        else Path(source).read_text()
    )
    scenario, phases = _toml_tables(text)
    return scenario_from_dict(
        {"name": scenario.get("name", "scenario"), "phases": phases}
    )


# ----------------------------------------------------------- the compiler
def compile_scenario(
    spec: ScenarioSpec,
    *,
    n_peers: int,
    n_slots: int,
    total_rounds: int,
    node_map=None,
    shard_ranges: list[tuple[int, int]] | None = None,
    n_shards: int | None = None,
) -> CompiledScenario:
    """Compile a validated spec to the device tables the engines consume.

    ``n_peers`` is the REAL peer count (node sets are declared over it),
    ``n_slots`` the state row count (pads included), ``node_map`` an
    optional peer-id→row mapping (the bucketed mesh's ``position``, the
    sharded matching row formula), ``shard_ranges`` the per-shard
    ``(row_lo, row_hi)`` spans for shard-scoped sets. Validates as a
    precondition — callers that already validated pay a cheap re-check.
    """
    spec.validate(
        total_rounds=total_rounds, n_peers=n_peers, n_shards=n_shards
    )
    import jax.numpy as jnp

    n_ph = len(spec.phases)
    phase_of_round = np.full(total_rounds + 1, n_ph, dtype=np.int32)
    loss = np.zeros(n_ph + 1, dtype=np.float32)
    delay = np.zeros(n_ph + 1, dtype=np.float32)
    leave = np.zeros(n_ph + 1, dtype=np.float32)
    join = np.zeros(n_ph + 1, dtype=np.float32)
    jburst = np.zeros(n_ph + 1, dtype=np.int32)
    burst = np.zeros((n_ph + 1, n_slots), dtype=bool)
    blackout = np.zeros((n_ph + 1, n_slots), dtype=bool)
    group_b = np.zeros((n_ph + 1, n_slots), dtype=bool)
    has_acc = any(p.accusers is not None for p in spec.phases)
    has_forge = any(p.forgers is not None for p in spec.phases)
    has_flood = any(p.floods is not None for p in spec.phases)
    accuser = np.zeros((n_ph + 1, n_slots), dtype=bool)
    forger = np.zeros((n_ph + 1, n_slots), dtype=bool)
    flooder = np.zeros((n_ph + 1, n_slots), dtype=bool)
    forge_fo = np.zeros(n_ph + 1, dtype=np.int32)
    flood_fo = np.zeros(n_ph + 1, dtype=np.int32)

    for i, p in enumerate(spec.phases):
        phase_of_round[p.start : p.end] = i
        loss[i] = p.loss
        delay[i] = p.delay
        leave[i] = p.churn_leave
        join[i] = p.churn_join
        jburst[i] = p.join_burst
        if p.churn_leave or p.churn_join:
            burst[i] = p.churn_nodes.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
        if p.partition is not None:
            group_b[i] = p.partition.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
        if p.blackout is not None:
            blackout[i] = p.blackout.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
        if p.accusers is not None:
            accuser[i] = p.accusers.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
        if p.forgers is not None:
            forger[i] = p.forgers.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
            forge_fo[i] = p.forge_fanout
        if p.floods is not None:
            flooder[i] = p.floods.resolve(
                n_peers, n_slots, node_map, shard_ranges
            )
            flood_fo[i] = p.flood_fanout

    return CompiledScenario(
        phase_of_round=jnp.asarray(phase_of_round),
        loss=jnp.asarray(loss),
        delay=jnp.asarray(delay),
        leave=jnp.asarray(leave),
        join=jnp.asarray(join),
        burst=jnp.asarray(burst),
        blackout=jnp.asarray(blackout),
        group_b=jnp.asarray(group_b),
        join_burst=jnp.asarray(jburst) if spec.uses_join_burst else None,
        accuser=jnp.asarray(accuser) if has_acc else None,
        forger=jnp.asarray(forger) if has_forge else None,
        flooder=jnp.asarray(flooder) if has_flood else None,
        forge_fanout=jnp.asarray(forge_fo) if has_forge else None,
        flood_fanout=jnp.asarray(flood_fo) if has_flood else None,
        name=spec.name,
        has_partition=any(p.partition is not None for p in spec.phases),
        has_blackout=any(p.blackout is not None for p in spec.phases),
        has_churn=any(p.churn_leave or p.churn_join for p in spec.phases),
        has_loss_delay=any(p.loss or p.delay for p in spec.phases),
        has_join_burst=spec.uses_join_burst,
        has_accusers=has_acc,
        has_forgers=has_forge,
        has_floods=has_flood,
        max_forge_fanout=spec.max_forge_fanout,
        max_flood_fanout=spec.max_flood_fanout,
        n_rounds=total_rounds,
    )
