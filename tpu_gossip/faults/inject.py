"""Runtime fault injection: the chaos engine's on-device half.

A compiled scenario (:mod:`tpu_gossip.faults.scenario`) is a pytree of
per-phase parameter tables plus a per-round phase index — jit-static in
STRUCTURE (which fault classes exist is decided at trace time via the
``has_*`` metadata) and traced in VALUE (phase boundaries, probabilities,
node masks), so one compile serves the whole time-phased schedule and the
round loop stays a single ``lax.scan``/``while_loop`` with the round
counter in the state acting as the scenario cursor.

Every fault draw comes from a dedicated per-round stream derived by
``fold_in(state.rng, FAULT_STREAM_SALT)`` — the round's 5-way protocol
split is untouched, so a quiescent scenario (or phases with zero
probabilities) leaves the no-scenario trajectory BIT-IDENTICAL, and all
draws are made at GLOBAL shape outside ``shard_map`` (threefry bits are
position-deterministic), which extends the local ↔ sharded bit-identity
contract (tests/sim/test_dist.py) to every scenario for free.

Fault classes and their semantics (docs/fault_model.md has the catalogue
and the modeling caveats):

- **loss** — each delivered (receiver, slot) bit is dropped with
  probability ``loss`` this round. Applied at the delivery interface (the
  merged incoming bitmap), i.e. last-hop receiver-side loss: exact
  per-edge loss for single-copy deliveries (the overwhelmingly common
  case under sampled push), a lower bound on multi-copy rounds.
- **delay** — surviving deliveries are deferred with probability
  ``delay`` into the state's ``fault_held`` buffer and re-offered next
  round, where they may defer again: geometric holding, mean extra
  latency ``delay/(1-delay)`` rounds. Held bits a receiver has meanwhile
  seen are dropped from the buffer (they would merge to nothing).
- **partition** — the swarm splits into two groups (``group_b`` mask);
  delivery runs once per group over group-masked transmit/transmitter/
  receptive and cross-group bits are discarded. Sends into the boundary
  are still billed (they were transmitted; the network ate them).
- **blackout** — nodes in the mask neither send, receive, nor heartbeat
  for the phase (the transient-outage sibling of churn: protocol state
  survives). The failure detector sees them exactly like silent-mode
  peers (reference Peer.py:437-439), so a blackout longer than the
  timeout produces dead declarations — which are PERMANENT, as in the
  reference's registry purge.
- **churn burst** — extra per-round leave/join probability over a node
  mask, folded into the engine's existing churn draws (same keys, same
  shapes — per-node thresholds change, the stream does not).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "FAULT_STREAM_SALT",
    "CompiledScenario",
    "RoundFaults",
    "FaultTelemetry",
    "faulted_dissemination",
    "scenario_dissemination",
    "drain_held",
]

# folds the round's root key (state.rng) into the fault stream — a
# derivation parallel to the protocol's 5-way split, never overlapping it.
# The value lives in the canonical stream registry (core/streams.py, where
# uniqueness is asserted at import); re-exported here for compatibility.
from tpu_gossip.core.streams import FAULT_STREAM_SALT  # noqa: E402


class RoundFaults(NamedTuple):
    """One round's fault parameters (traced scalars + (N,) node masks)."""

    loss: jax.Array  # f32 — P(drop a delivered (receiver, slot) bit)
    delay: jax.Array  # f32 — P(defer a surviving delivery one round)
    leave: jax.Array  # f32 — extra per-round leave probability (burst rows)
    join: jax.Array  # f32 — extra per-round rejoin probability (burst rows)
    burst: jax.Array  # bool (N,) — rows the churn burst applies to
    blackout: jax.Array  # bool (N,) — rows cut off from the network
    group_b: jax.Array  # bool (N,) — partition side B (False = side A)
    join_burst: jax.Array  # i32 — extra growth admissions this round (growth/)
    # Byzantine adversaries (docs/adversarial_model.md): the ``has_*``
    # flags are static, so absent attack classes read scalar zero
    # placeholders consumers never touch
    accuser: jax.Array  # bool (N,) — rows emitting false dead-verdicts
    forger: jax.Array  # bool (N,) — rows forging third-party heartbeats
    flooder: jax.Array  # bool (N,) — rows replaying their seen bitmaps
    forge_fanout: jax.Array  # i32 — forged heartbeats per forger per round
    flood_fanout: jax.Array  # i32 — replay targets per flooder per round


class FaultTelemetry(NamedTuple):
    """Per-round fault counters for RoundStats (all scalar int32)."""

    msgs_dropped: jax.Array  # deliveries eaten by the loss fault
    msgs_held: jax.Array  # deliveries sitting in the delay buffer
    msgs_delivered: jax.Array  # deliveries that landed this round


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CompiledScenario:
    """A fault schedule compiled to device tables (faults/scenario.py).

    ``phase_of_round[o]`` maps the 0-based round offset to a row of the
    per-phase tables; row ``P`` (the last) is the quiescent no-fault row,
    which also covers every round past the schedule (a healed network).
    The ``has_*`` flags are STATIC: they decide trace structure (e.g. the
    two-pass partition delivery exists only when some phase partitions),
    so a scenario without a fault class costs nothing for it.
    """

    phase_of_round: jax.Array  # int32 (R+1,)
    loss: jax.Array  # f32 (P+1,)
    delay: jax.Array  # f32 (P+1,)
    leave: jax.Array  # f32 (P+1,)
    join: jax.Array  # f32 (P+1,)
    burst: jax.Array  # bool (P+1, N)
    blackout: jax.Array  # bool (P+1, N)
    group_b: jax.Array  # bool (P+1, N)
    # growth admission waves (growth/): extra joins/round per phase, on
    # top of the growth schedule's base rate — zero table without
    # join_burst phases. Meaningless without an active growth schedule
    # (run_sim rejects the combination at parse time).
    join_burst: jax.Array | None = None  # i32 (P+1,)
    # Byzantine adversary tables (docs/adversarial_model.md) — None
    # unless the matching phase key appears, so a crash-fault-only
    # scenario's pytree (and its cost) is unchanged
    accuser: jax.Array | None = None  # bool (P+1, N)
    forger: jax.Array | None = None  # bool (P+1, N)
    flooder: jax.Array | None = None  # bool (P+1, N)
    forge_fanout: jax.Array | None = None  # i32 (P+1,)
    flood_fanout: jax.Array | None = None  # i32 (P+1,)
    name: str = dataclasses.field(default="scenario", metadata=dict(static=True))
    has_partition: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_blackout: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_churn: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_loss_delay: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_join_burst: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_accusers: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_forgers: bool = dataclasses.field(default=False, metadata=dict(static=True))
    has_floods: bool = dataclasses.field(default=False, metadata=dict(static=True))
    max_forge_fanout: int = dataclasses.field(default=0, metadata=dict(static=True))
    max_flood_fanout: int = dataclasses.field(default=0, metadata=dict(static=True))
    n_rounds: int = dataclasses.field(default=0, metadata=dict(static=True))

    @property
    def has_adversary(self) -> bool:
        """Static: any Byzantine attack class present (the adversary
        stream is folded — and the quorum planes required — only then)."""
        return self.has_accusers or self.has_forgers or self.has_floods

    def at_round(self, rnd: jax.Array) -> RoundFaults:
        """The fault parameters governing round ``rnd`` (1-based, traced).

        Rounds past the schedule clamp onto the quiescent row, so a
        run-to-coverage loop that outlives the scenario finishes on a
        healed network and any held deliveries drain (``delay`` is 0
        there).
        """
        o = jnp.clip(rnd - 1, 0, self.phase_of_round.shape[0] - 1)
        ph = self.phase_of_round[o]
        zb = jnp.zeros((), dtype=bool)
        zi = jnp.zeros((), dtype=jnp.int32)
        return RoundFaults(
            loss=self.loss[ph],
            delay=self.delay[ph],
            leave=self.leave[ph],
            join=self.join[ph],
            burst=self.burst[ph],
            blackout=self.blackout[ph],
            group_b=self.group_b[ph],
            join_burst=zi if self.join_burst is None else self.join_burst[ph],
            # absent attack classes hand consumers a scalar placeholder
            # they never read (the has_* flags are static) — the
            # join_burst pattern, so absent adversaries cost nothing
            accuser=zb if self.accuser is None else self.accuser[ph],
            forger=zb if self.forger is None else self.forger[ph],
            flooder=zb if self.flooder is None else self.flooder[ph],
            forge_fanout=(
                zi if self.forge_fanout is None else self.forge_fanout[ph]
            ),
            flood_fanout=(
                zi if self.flood_fanout is None else self.flood_fanout[ph]
            ),
        )


def faulted_dissemination(
    scenario: CompiledScenario,
    rf: RoundFaults,
    deliver: Callable,
    transmit: jax.Array,
    transmitter: jax.Array,
    receptive: jax.Array,
    held: jax.Array,
    seen: jax.Array,
    k_push: jax.Array,
    k_pull: jax.Array,
    k_fault: jax.Array,
    flood_ok: jax.Array | None = None,
    k_flood: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, FaultTelemetry]:
    """Run one round's dissemination with the scenario's faults applied.

    ``deliver(tx, transmitter, receptive, k_push, k_pull) -> (incoming,
    msgs)`` is the engine's dissemination core (local XLA/kernel, bucketed
    mesh, matching mesh) — the fault structure wraps it identically on
    every engine, which is what makes a scenario round bit-identical
    between the local and sharded runs of the same engine family.

    Returns ``(incoming, msgs_sent, tx_effective, new_held, telemetry)``:
    ``tx_effective`` is the transmit bitmap that actually left senders
    this round (blackout senders pushed nothing — forward-once
    bookkeeping must not mark them), ``new_held`` the delay buffer to
    carry in the state.

    Loss/delay draws are made EVERY round of a scenario that contains any
    loss/delay phase, at full (N, M) shape regardless of the active phase
    (quiescent thresholds make them no-ops): each draw's stream position
    depends only on the round number, so phase edits never shift later
    rounds' randomness and checkpoint resume mid-scenario replays
    identically. A scenario WITHOUT loss/delay phases skips the stage
    entirely (``has_loss_delay`` is static) — the keys are derived
    independently, so skipping moves no other draw — keeping the
    "absent fault classes cost nothing" contract.
    """
    k_loss, k_delay, k_push_b, k_pull_b = jax.random.split(k_fault, 4)

    if scenario.has_partition:
        ga = ~rf.group_b
        gb = rf.group_b
        if scenario.has_blackout:
            ga = ga & ~rf.blackout
            gb = gb & ~rf.blackout
        ca, cb = ga[:, None], gb[:, None]
        # one delivery pass per side, each over side-masked participants;
        # a pass's cross-boundary bits are discarded receiver-side (they
        # were billed — the network dropped them at the boundary)
        inc_a, msgs_a = deliver(
            transmit & ca, transmitter & ca, receptive & ca, k_push, k_pull
        )
        # the B pass only runs while a partition phase is ACTIVE: on
        # quiescent rounds group B is empty and the pass would contribute
        # exactly (zeros, 0), so lax.cond skips its full delivery cost at
        # runtime. The predicate comes from replicated scenario tables —
        # every shard takes the same branch, the same replicated-control
        # regime as the collectives inside run_until_coverage_dist's
        # while_loop — and the B keys are derived positionally either
        # way, so no other draw's stream position moves.
        inc_b, msgs_b = jax.lax.cond(
            gb.any(),
            lambda: deliver(
                transmit & cb, transmitter & cb, receptive & cb,
                k_push_b, k_pull_b,
            ),
            lambda: (
                jnp.zeros_like(transmit),
                jnp.zeros((), dtype=jnp.int32),
            ),
        )
        raw = (inc_a & ca) | (inc_b & cb)
        msgs = msgs_a + msgs_b
        recv_ok = ga | gb
    elif scenario.has_blackout:
        lv = ~rf.blackout
        lc = lv[:, None]
        raw, msgs = deliver(
            transmit & lc, transmitter & lc, receptive & lc, k_push, k_pull
        )
        raw = raw & lc
        recv_ok = lv
    else:
        raw, msgs = deliver(transmit, transmitter, receptive, k_push, k_pull)
        recv_ok = None

    if scenario.has_floods:
        # flood attack: each active flooder replays its FULL seen bitmap
        # at flood_fanout sampled targets — pure duplicate-replay
        # pressure on the dedup/Bloom plane (most landed bits are
        # already-seen, so the damage is wire cost plus a poisoned
        # duplicate-saturation signal for the AIMD controller). Flood
        # traffic is ordinary network traffic: it respects partition
        # boundaries and blacked-out receivers, and the merged bits ride
        # the same loss/delay stage below. Draws land at global shape
        # from the adversary stream every round of a flood-carrying
        # scenario (quiescent phases mask them — stream positions depend
        # only on the round, the loss/delay convention).
        n, fw = seen.shape[0], scenario.max_flood_fanout
        tgt = jax.random.randint(k_flood, (n, fw), 0, n)
        act = flood_ok[:, None] & (jnp.arange(fw)[None, :] < rf.flood_fanout)
        if scenario.has_partition:
            act = act & (rf.group_b[tgt] == rf.group_b[:, None])
        if scenario.has_blackout:
            act = act & ~rf.blackout[tgt]
        payload = seen[:, None, :] & act[:, :, None]  # (N, Fw, M)
        raw = raw | jnp.zeros_like(raw).at[tgt.reshape(-1)].max(
            payload.reshape(n * fw, -1), mode="drop"
        )
        msgs = msgs + jnp.sum(
            seen.sum(-1, dtype=jnp.int32) * act.sum(-1, dtype=jnp.int32)
        )

    if scenario.has_loss_delay:
        # loss: last-hop drop on the merged delivery bitmap
        keep = jax.random.uniform(k_loss, raw.shape) >= rf.loss
        dropped = jnp.sum(raw & ~keep, dtype=jnp.int32)
        surviving = raw & keep

        # delay: geometric holding in the state's fault_held buffer. Held
        # bits release only to receivers that can currently receive (a
        # blacked-out receiver's backlog waits out the phase); releases
        # merge with fresh deliveries and may defer again. Bits the
        # receiver has since seen are dropped from the buffer — they
        # would merge to nothing.
        release = held if recv_ok is None else held & recv_ok[:, None]
        merged = surviving | release
        defer = jax.random.uniform(k_delay, raw.shape) < rf.delay
        incoming = merged & ~defer
        new_held = merged & defer & ~seen
        if recv_ok is not None:
            new_held = new_held | (held & ~recv_ok[:, None])
        telem = FaultTelemetry(
            msgs_dropped=dropped,
            msgs_held=jnp.sum(new_held, dtype=jnp.int32),
            msgs_delivered=jnp.sum(incoming, dtype=jnp.int32),
        )
    else:
        # no loss/delay phase anywhere in the schedule: the (N, M) draws
        # and the hold-buffer merge would be pure per-round overhead —
        # skip the stage (telemetry stays 0, like every absent fault)
        incoming, new_held = raw, held
        z = jnp.zeros((), dtype=jnp.int32)
        telem = FaultTelemetry(msgs_dropped=z, msgs_held=z, msgs_delivered=z)

    tx_eff = (
        transmit & (~rf.blackout)[:, None] if scenario.has_blackout else transmit
    )
    return incoming, msgs, tx_eff, new_held, telem


def scenario_dissemination(
    scenario: CompiledScenario,
    state,
    rnd: jax.Array,
    transmit: jax.Array,
    transmitter: jax.Array,
    receptive: jax.Array,
    k_push: jax.Array,
    k_pull: jax.Array,
    deliver: Callable,
    k_flood: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, FaultTelemetry, RoundFaults]:
    """The whole per-round scenario head, shared by all three engines.

    Looks up the round's fault parameters, derives the fault stream from
    the round's root key (``fold_in(state.rng, FAULT_STREAM_SALT)`` — the
    protocol's 5-way split is untouched), and runs
    :func:`faulted_dissemination` around the engine's ``deliver`` core.
    Returns ``(incoming, msgs_sent, tx_effective, new_held, telemetry,
    round_faults)`` — the engine feeds the last three to
    ``advance_round(..., faults=rf, churn_faults=scenario.has_churn,
    fault_held=new_held, fstats=telemetry)``. Existing in ONE place so the
    engines cannot drift: any change to the fault plumbing lands on every
    engine at once, which is what keeps the bit-identity contract honest.

    ``k_flood`` is the flood-replay child of the adversary stream
    (derived ONCE per round by the shared driver,
    ``sim.stages.run_protocol_round`` — one ``fold_in`` per (parent,
    salt), the lineage contract); required exactly when the scenario
    carries flood phases.
    """
    rf = scenario.at_round(rnd)
    k_fault = jax.random.fold_in(state.rng, FAULT_STREAM_SALT)
    flood_ok = None
    if scenario.has_floods:
        flood_ok = (
            rf.flooder & state.alive & ~state.declared_dead
            & ~state.quarantine
        )
        if scenario.has_blackout:
            flood_ok = flood_ok & ~rf.blackout
    incoming, msgs, tx_eff, new_held, telem = faulted_dissemination(
        scenario, rf, deliver, transmit, transmitter, receptive,
        state.fault_held, state.seen, k_push, k_pull, k_fault,
        flood_ok, k_flood,
    )
    return incoming, msgs, tx_eff, new_held, telem, rf


def drain_held(state):
    """One-shot release of the delay buffer OUTSIDE any scenario.

    Resuming a mid-delay checkpoint WITHOUT its scenario leaves
    ``fault_held`` frozen — the no-scenario round path carries it
    untouched on purpose (merging an almost-always-empty buffer every
    round would tax the hot loop's HBM traffic for nothing). This helper
    is the explicit drain for that case: held deliveries merge through
    the same receptive gate a round would apply (alive, not declared
    dead, not SIR-removed per slot), ``infected_round`` latches at the
    current round, and the buffer clears. Pure; call once after load.
    """
    import dataclasses as _dc

    from tpu_gossip.core.state import saturate_round

    active = state.alive & ~state.declared_dead
    inc = state.fault_held & active[:, None] & ~state.recovered
    latch = (inc & ~state.seen) & (state.infected_round < 0)
    return _dc.replace(
        state,
        seen=state.seen | inc,
        infected_round=jnp.where(
            latch, saturate_round(state.round, state.infected_round.dtype),
            state.infected_round,
        ),
        fault_held=jnp.zeros_like(state.fault_held),
    )
