"""Streaming serving plane: sustained many-message traffic on the
slot/Bloom dedup engine (docs/streaming_plane.md).

``compile_stream`` (traffic/plan.py) turns an injection-rate + origin-law
config into a :class:`CompiledStream` pytree; ``apply_stream`` and
``slot_expiry`` (traffic/engine.py) run as the streaming stage of the
shared ``sim.engine.advance_round`` on every delivery engine. The
injection draws come from the registered ``TRAFFIC_STREAM_SALT`` stream
(core/streams.py) at global shape, so the local ↔ sharded bit-identity
contract extends to loaded swarms.
"""

from tpu_gossip.traffic.engine import (
    TRAFFIC_STREAM_SALT,
    StreamTelemetry,
    apply_stream,
    slot_expiry,
)
from tpu_gossip.traffic.plan import (
    ORIGIN_LAWS,
    CompiledStream,
    StreamError,
    compile_stream,
    default_max_inject,
    min_feasible_ttl,
)

__all__ = [
    "TRAFFIC_STREAM_SALT",
    "StreamTelemetry",
    "apply_stream",
    "slot_expiry",
    "ORIGIN_LAWS",
    "CompiledStream",
    "StreamError",
    "compile_stream",
    "default_max_inject",
    "min_feasible_ttl",
]
