"""Streaming serving plane: sustained many-message traffic on the
slot/Bloom dedup engine (docs/streaming_plane.md).

``compile_stream`` (traffic/plan.py) turns an injection-rate + origin-law
config into a :class:`CompiledStream` pytree; ``apply_stream`` and
``slot_expiry`` (traffic/engine.py) run as the streaming stage of the
shared ``sim.engine.advance_round`` on every delivery engine. The
injection draws come from the registered ``TRAFFIC_STREAM_SALT`` stream
(core/streams.py) at global shape, so the local ↔ sharded bit-identity
contract extends to loaded swarms.

``apply_arrivals`` (traffic/ingest.py) is the deterministic twin fed by
the live serving frontend (serve/): host-batched REAL arrivals land with
the same lease/Bloom semantics but zero randomness, so a recorded trace
replays bit for bit.
"""

from tpu_gossip.traffic.engine import (
    TRAFFIC_STREAM_SALT,
    StreamTelemetry,
    apply_stream,
    slot_expiry,
)
from tpu_gossip.traffic.ingest import (
    IngestError,
    IngestPlan,
    IngestTelemetry,
    InjectBatch,
    apply_arrivals,
    empty_batch,
    make_batch,
)
from tpu_gossip.traffic.plan import (
    ORIGIN_LAWS,
    CompiledStream,
    StreamError,
    compile_stream,
    default_max_inject,
    min_feasible_ttl,
)

__all__ = [
    "TRAFFIC_STREAM_SALT",
    "StreamTelemetry",
    "apply_stream",
    "slot_expiry",
    "IngestError",
    "IngestPlan",
    "IngestTelemetry",
    "InjectBatch",
    "apply_arrivals",
    "empty_batch",
    "make_batch",
    "ORIGIN_LAWS",
    "CompiledStream",
    "StreamError",
    "compile_stream",
    "default_max_inject",
    "min_feasible_ttl",
]
