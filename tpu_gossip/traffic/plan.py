"""Streaming workload plans: arrival process + origin law, compiled host-side.

Every number in the tree before this plane measured ONE epidemic to 99%
coverage. Production gossip serves a *stream*: messages injected every
round by millions of users (*Reliable Probabilistic Gossip over
Large-Scale Random Topologies*, PAPERS.md, frames the per-message
reliability regime under sustained injection). A
:class:`CompiledStream` is the jit-static description of that workload —
the traffic twin of :class:`~tpu_gossip.faults.CompiledScenario` and
:class:`~tpu_gossip.growth.CompiledGrowth`:

- **arrival process** — per-round arrival counts are Poisson(``rate``);
  with ``burst_every > 0`` every ``burst_every``-th round draws at
  ``rate * burst_mult`` instead (a deterministic on/off modulated Poisson
  — round-indexed, so checkpoint resume and phase edits never shift later
  rounds' randomness). Arrivals beyond the static ``max_inject`` batch
  are dropped that round (sized to the burst rate's +6σ tail by default,
  so drops are a <1e-8 event unless deliberately undersized).
- **origin law** — "uniform" draws origins uniformly over the INITIAL
  membership (``origin_rows``); "degree" draws degree-proportionally via
  a uniform index into the CSR endpoint list (the repeated-endpoints
  trick the re-wiring draws already use — needs an exported CSR);
  "hotspot" mixes a uniform draw with a concentrated draw over the
  ``hot_n`` lowest peer ids (the hubs, in every power-law builder here)
  at weight ``hot_weight``.
- **slot law** — each message draws ``k_hashes`` uniform slots (the
  device-side analogue of :func:`~tpu_gossip.core.state.message_slots`'
  uniform hash planes): k=1 conflates on a live slot, k>=2 is Bloom
  semantics (suppressed iff ALL k slots carry live leases). The measured
  rates conform to ``sim.metrics.expected_conflations`` /
  ``bloom_false_positive_rate`` (tests/sim/test_traffic.py).

Layout-blindness works exactly like the growth plane's: ``origin_rows``
is the id-ordered table of REAL peer state rows, so a local and a
sharded run sharing a layout draw identical origins — the streaming
extension of the bit-identity contract.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np

__all__ = [
    "StreamError",
    "CompiledStream",
    "compile_stream",
    "default_max_inject",
    "min_feasible_ttl",
    "ORIGIN_LAWS",
]

ORIGIN_LAWS = ("uniform", "degree", "hotspot")


class StreamError(ValueError):
    """A streaming config that cannot mean what it says (compile time)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CompiledStream:
    """A streaming workload compiled to device tables.

    Traced leaves carry the workload's tables; static fields decide trace
    structure (batch shape, origin law, Bloom width, burst cadence) —
    one compile serves the whole run, and a zero-``rate`` stream is a
    masked no-op whose trajectory is bit-identical to ``stream=None``
    (test-pinned; the injection stage draws from its own registered
    PRNG stream, so the protocol's 5-way split never moves).
    """

    rate: jax.Array  # f32 scalar — mean arrivals/round (Poisson)
    origin_rows: jax.Array  # int32 (n_real,) — id-ordered real-peer rows
    hot_rows: jax.Array  # int32 (hot_n,) — hotspot origin rows (1 zero if unused)
    ttl: int = dataclasses.field(metadata=dict(static=True))
    max_inject: int = dataclasses.field(metadata=dict(static=True))
    k_hashes: int = dataclasses.field(metadata=dict(static=True))
    origins: str = dataclasses.field(metadata=dict(static=True))
    hot_weight: float = dataclasses.field(metadata=dict(static=True))
    burst_every: int = dataclasses.field(metadata=dict(static=True))
    burst_mult: float = dataclasses.field(metadata=dict(static=True))


def default_max_inject(peak_rate: float) -> int:
    """The static per-round arrival batch a peak Poisson rate needs: the
    +6σ tail makes a dropped arrival a <1e-8 event per round. Callers
    pinning one compile across several rates (bench.py's saturation
    curve) pass their LARGEST rate here."""
    return max(
        int(math.ceil(peak_rate + 6.0 * math.sqrt(max(peak_rate, 1.0)))), 4
    )


def min_feasible_ttl(n_peers: int, fanout: int, mode: str = "push") -> int:
    """The shortest slot TTL that can plausibly cover the swarm.

    A sampled epidemic multiplies its infected set by ~(1 + fanout) per
    round until saturation, so coverage needs ~log_{1+fanout}(n) rounds
    plus a tail allowance for the power-law families' low-degree fringe
    (flood covers in diameter rounds — strictly faster, same bound kept
    for one conservative contract). A lease shorter than this recycles
    every message before it can possibly cover — a config error the CLI
    rejects at parse time, not a saturation measurement.
    """
    growth_rate = max(2, 1 + max(fanout, 1))
    return int(math.ceil(math.log(max(n_peers, 2)) / math.log(growth_rate))) + 4


def compile_stream(
    *,
    rate: float,
    msg_slots: int,
    ttl: int,
    origin_rows: np.ndarray,
    origins: str = "uniform",
    k_hashes: int = 1,
    hot_frac: float = 0.01,
    hot_weight: float = 0.9,
    burst_every: int = 0,
    burst_mult: float = 4.0,
    max_inject: int | None = None,
) -> CompiledStream:
    """Compile a streaming workload for one engine's slot layout.

    ``origin_rows`` lists the REAL peer state rows in peer-id order (the
    same id→row hook the scenario and growth compilers take) — origins
    are drawn over the initial membership; grown peers are not
    origin-addressable, exactly like scenario node sets. Validates as a
    precondition: impossible workloads are config errors before anything
    traces.
    """
    import jax.numpy as jnp

    if rate < 0:
        raise StreamError(f"injection rate {rate} must be >= 0")
    if ttl < 1:
        raise StreamError(f"slot TTL {ttl} must be >= 1 round")
    if not (1 <= k_hashes <= msg_slots):
        raise StreamError(
            f"k_hashes={k_hashes} outside [1, msg_slots={msg_slots}] — the "
            "Bloom planes live in the slot dimension"
        )
    if origins not in ORIGIN_LAWS:
        raise StreamError(f"unknown origin law {origins!r}; choose from {ORIGIN_LAWS}")
    if burst_every < 0 or burst_mult <= 0:
        raise StreamError("burst_every must be >= 0 and burst_mult > 0")
    origin_rows = np.asarray(origin_rows, dtype=np.int64)
    if origin_rows.ndim != 1 or origin_rows.size == 0:
        raise StreamError("origin_rows must be a non-empty 1-D row table")
    peak = rate * (burst_mult if burst_every > 0 else 1.0)
    if max_inject is None:
        max_inject = default_max_inject(peak)
    if max_inject < 1:
        raise StreamError(f"max_inject={max_inject} must be >= 1")
    if not (0.0 <= hot_weight <= 1.0):
        raise StreamError(f"hot_weight={hot_weight} outside [0, 1]")
    if origins == "hotspot":
        if not (0.0 < hot_frac <= 1.0):
            raise StreamError(f"hot_frac={hot_frac} outside (0, 1]")
        hot_n = max(1, int(hot_frac * origin_rows.size))
        hot_rows = origin_rows[:hot_n]  # lowest peer ids = the hubs
    else:
        hot_rows = np.zeros(1, dtype=np.int64)
    return CompiledStream(
        rate=jnp.asarray(rate, dtype=jnp.float32),
        origin_rows=jnp.asarray(origin_rows, dtype=jnp.int32),
        hot_rows=jnp.asarray(hot_rows, dtype=jnp.int32),
        ttl=int(ttl),
        max_inject=int(max_inject),
        k_hashes=int(k_hashes),
        origins=str(origins),
        hot_weight=float(hot_weight),
        burst_every=int(burst_every),
        burst_mult=float(burst_mult),
    )
