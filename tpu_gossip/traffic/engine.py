"""In-round injection + slot age-out: the streaming engine's on-device half.

The streaming stage runs INSIDE the jitted round as part of
``sim.engine.advance_round`` — shared by all three delivery engines, so
the serving plane exists once and cannot drift between them:

- **age-out** (:func:`slot_expiry`): a slot whose lease is ``ttl`` rounds
  old is recycled — its column of every slot array (seen / forwarded /
  infected_round / recovered / fault_held) is cleared THROUGH the fused
  round tail (``kernels.round_tail``'s ``expired`` mask rides the same
  producing selects as the churn fresh mask), and its lease resets to
  free. The (N, M) bitmap is thereby a SLIDING WINDOW over live
  messages, the bounded-memory dedup regime docs/dedup_semantics.md
  specifies, now under sustained load.
- **injection** (:func:`apply_stream`): the round's arrivals (Poisson or
  burst-modulated — traffic/plan.py) each draw an origin by the
  configured law and ``k_hashes`` uniform slots, then land
  SEQUENTIALLY: with k=1 a message landing on a live lease is CONFLATED
  (it rides the incumbent epidemic — counted, never suppressed); with
  k>=2 a message whose k slots ALL carry live leases is a Bloom false
  positive and is suppressed at ingestion (the classic trade,
  docs/dedup_semantics.md). Free slots among a landing message's draws
  take its lease. The origin's bits are set post-tail, so a round-r
  injection first transmits in round r+1.

Every draw comes from ``fold_in(state.rng, TRAFFIC_STREAM_SALT)`` at
GLOBAL shape outside ``shard_map`` — a derivation parallel to the
protocol's 5-way split and the fault/growth streams, overlapping none of
them — so the local ↔ sharded bit-identity contract extends to loaded
swarms, and a zero-rate stream reproduces the fixed single-epidemic
trajectory bit for bit (both test-pinned, tests/sim/test_traffic.py).
All shapes are static (``max_inject`` arrivals drawn every round
regardless of the traced count — stream positions depend only on the
round, so rate edits never shift later rounds' randomness) and the
per-batch scan carries only the (M,) lease table.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_gossip.core.state import saturate_round
from tpu_gossip.core.streams import TRAFFIC_STREAM_SALT

__all__ = [
    "TRAFFIC_STREAM_SALT",
    "StreamTelemetry",
    "slot_expiry",
    "apply_stream",
]


class StreamTelemetry(NamedTuple):
    """Per-round streaming counters for RoundStats (all scalar int32)."""

    offered: jax.Array  # arrivals the process produced this round
    injected: jax.Array  # arrivals that landed (live origin, not suppressed)
    conflated: jax.Array  # k=1: landed on a live lease; k>=2: Bloom-FP suppressed
    expired: jax.Array  # leases the age-out recycled this round


def slot_expiry(slot_lease: jax.Array, rnd: jax.Array, ttl: int) -> jax.Array:
    """(M,) bool — slots whose lease ages out at round ``rnd``.

    A message injected at round r expires at round r + ttl: it had
    exactly ``ttl`` dissemination rounds (its injection round r set bits
    post-tail, rounds r+1..r+ttl relayed them, round r+ttl's tail clears
    the column). Free slots (lease -1) never expire.
    """
    return (slot_lease >= 0) & (rnd - slot_lease >= ttl)


def apply_stream(
    stream,
    rng: jax.Array,
    rnd: jax.Array,
    expired_count: jax.Array,
    *,
    seen: jax.Array,
    infected_round: jax.Array,
    slot_lease: jax.Array,
    row_ptr: jax.Array,
    col_idx: jax.Array,
    exists: jax.Array,
    alive: jax.Array,
    declared_dead: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, StreamTelemetry]:
    """Inject one round's arrivals; returns (seen, infected_round,
    slot_lease, telemetry).

    ``rng`` is the round's ROOT key (``state.rng``) — the traffic stream
    derives by ``fold_in`` and consumes nothing of the protocol's 5-way
    split. Runs AFTER the fused tail and the churn/growth row stages, so
    origins are gated on the round's FINAL liveness (an arrival whose
    drawn origin is down is lost at ingestion — offered but not injected
    — exactly a user knocking on a dead peer) and a slot the age-out just
    recycled is immediately re-leasable. Slot draws are uniform, the
    device-side analogue of :func:`~tpu_gossip.core.state.message_slots`'
    independent hash planes, so the measured conflation/Bloom-FP rates
    conform to the closed-form predictors in ``sim.metrics``.
    """
    n = exists.shape[0]
    m = seen.shape[1]
    j, k = stream.max_inject, stream.k_hashes

    k_stream = jax.random.fold_in(rng, TRAFFIC_STREAM_SALT)
    k_count, k_origin, k_hot, k_slot, k_fb = jax.random.split(k_stream, 5)

    rate = stream.rate
    if stream.burst_every > 0:
        burst = (rnd % stream.burst_every) == 0
        rate = rate * jnp.where(burst, stream.burst_mult, 1.0)
    n_arr = jnp.minimum(
        jax.random.poisson(k_count, rate, dtype=jnp.int32), j
    )
    live = jnp.arange(j) < n_arr

    if stream.origins == "degree":
        # uniform index into the CSR endpoint list IS degree-proportional
        # sampling (the re-wiring draws' repeated-endpoints trick); draw
        # over the REAL edge span, not a remat capacity tail
        if col_idx.shape[0] == 1 and row_ptr.shape[0] > 3:
            raise ValueError(
                "degree-weighted stream origins read the CSR endpoint "
                "list, but this graph was built without one "
                "(matching_powerlaw_graph(export_csr=False)); rebuild "
                "with export_csr=True or use origins='uniform'"
            )
        e_real = jnp.maximum(row_ptr[-1], 1)
        draw = col_idx[
            jax.random.randint(k_origin, (j,), 0, e_real)
        ].astype(jnp.int32)
        # an endpoint draw can land on an erased/pad entry (device-built
        # CSRs point erased edges at the sentinel row) — fall back to a
        # uniform member draw instead of losing the arrival: the law is
        # degree-weighted with an O(erasure-rate) uniform contamination,
        # and the realized injection rate stays the configured one
        fallback = stream.origin_rows[
            jax.random.randint(k_fb, (j,), 0, stream.origin_rows.shape[0])
        ]
        origins = jnp.where(
            exists[jnp.clip(draw, 0, n - 1)], draw, fallback
        )
    elif stream.origins == "hotspot":
        k_hot_pick, k_hot_row = jax.random.split(k_hot)
        uni = stream.origin_rows[
            jax.random.randint(k_origin, (j,), 0, stream.origin_rows.shape[0])
        ]
        hot = stream.hot_rows[
            jax.random.randint(k_hot_row, (j,), 0, stream.hot_rows.shape[0])
        ]
        pick_hot = jax.random.uniform(k_hot_pick, (j,)) < stream.hot_weight
        origins = jnp.where(pick_hot, hot, uni)
    else:  # uniform over the initial membership
        origins = stream.origin_rows[
            jax.random.randint(k_origin, (j,), 0, stream.origin_rows.shape[0])
        ]

    safe_o = jnp.clip(origins, 0, n - 1)
    ok = (
        live
        & exists[safe_o]
        & alive[safe_o]
        & ~declared_dead[safe_o]
    )
    slots = jax.random.randint(k_slot, (j, k), 0, m).astype(jnp.int32)

    # sequential landing over the batch: arrival i+1 sees the leases
    # arrival i took (the per-message semantics the closed-form
    # predictors assume). The scan carries only the (M,) lease table —
    # all draws happen above, outside the loop (one trace, no
    # loop-invariant key redraws)
    def land(lease, x):
        sl, ok_i = x  # (k,) int32, scalar bool
        cur = lease[sl]
        leased = cur >= 0
        if k == 1:
            suppressed = jnp.zeros((), dtype=bool)
            conf = ok_i & leased[0]
        else:
            all_leased = jnp.all(leased)
            suppressed = all_leased
            conf = ok_i & all_leased
        landed = ok_i & ~suppressed
        # free slots among the draws take the lease; live leases keep
        # their (older, hence smaller) injection round under max. The
        # lease plane is the narrow int16 registry width (core.state.
        # PLANES): the round cursor SATURATES at ROUND_CAP so a campaign
        # past the cap ages leases out early instead of wrapping into
        # the free-slot -1 sentinel and losing the lease entirely
        contrib = jnp.where(
            landed & ~leased, saturate_round(rnd, lease.dtype), -1
        ).astype(lease.dtype)
        lease = lease.at[sl].max(contrib)
        return lease, (landed, conf)

    slot_lease, (landed, conflated) = jax.lax.scan(
        land, slot_lease, (slots, ok)
    )

    rows = jnp.where(landed, safe_o, n)
    inj = (
        jnp.zeros_like(seen)
        .at[
            jnp.broadcast_to(rows[:, None], (j, k)).reshape(-1),
            slots.reshape(-1),
        ]
        .set(True, mode="drop")
    )
    seen = seen | inj
    # like the lease writes above, the latch narrows to the plane's
    # declared int16 width, saturated at ROUND_CAP
    infected_round = jnp.where(
        inj & (infected_round < 0),
        saturate_round(rnd, infected_round.dtype),
        infected_round,
    )

    telem = StreamTelemetry(
        offered=n_arr,
        injected=jnp.sum(landed, dtype=jnp.int32),
        conflated=jnp.sum(conflated, dtype=jnp.int32),
        expired=expired_count.astype(jnp.int32),
    )
    return seen, infected_round, slot_lease, telem
