"""Deterministic arrival injection: the serving frontend's device half.

The streaming plane (``apply_stream``) *synthesizes* traffic from a
registered PRNG stream. The serving plane (serve/) receives REAL
traffic over sockets: the host frontend batches each round window's
accepted arrivals into static-shape tensors — an :class:`InjectBatch` —
and :func:`apply_arrivals` lands them with EXACTLY the streaming
engine's per-message semantics (sequential landing over the lease
table, k=1 conflation / k>=2 Bloom suppression, post-tail bit sets) but
ZERO randomness: origins and slots are data, not draws. The batch is
therefore the whole injection — replaying a recorded sequence of
batches through this function reproduces the live run bit for bit
(serve/trace.py's contract), and a zero-``count`` batch is a masked
no-op whose trajectory is bit-identical to ``inject=None``.

Static shapes: every batch carries ``max_inject`` rows regardless of
the traced ``count`` (entries past ``count`` are dead — masked out, not
read). Arrivals beyond ``max_inject`` in one round window are NEVER
dropped by the engine: the frontend carries them into the next window
and bills them to ``overflow`` so saturation is visible in RoundStats
(``ingest_overflow``), not silent.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.state import message_slots, saturate_round

__all__ = [
    "IngestError",
    "IngestPlan",
    "InjectBatch",
    "IngestTelemetry",
    "empty_batch",
    "make_batch",
    "apply_arrivals",
]


class IngestError(ValueError):
    """An ingest config that cannot mean what it says (compile time)."""


@dataclasses.dataclass(frozen=True)
class IngestPlan:
    """Static shape contract between the host frontend and the device
    injection stage: every round's batch is ``(max_inject,)`` origins ×
    ``(max_inject, k_hashes)`` slots, so ONE compile serves the whole
    serving session. ``k_hashes`` follows the streaming plane's Bloom
    semantics (k=1 conflates on a live lease, k>=2 suppresses only when
    all k slots are leased)."""

    msg_slots: int
    max_inject: int
    k_hashes: int = 1

    def __post_init__(self):
        if self.max_inject < 1:
            raise IngestError(f"max_inject={self.max_inject} must be >= 1")
        if not (1 <= self.k_hashes <= self.msg_slots):
            raise IngestError(
                f"k_hashes={self.k_hashes} outside [1, msg_slots="
                f"{self.msg_slots}] — the Bloom planes live in the slot "
                "dimension"
            )


class InjectBatch(NamedTuple):
    """One round window's accepted arrivals, at static shape (traced).

    ``origins`` are STATE ROWS (the engine's layout — sharded callers
    map peer ids through their plan's ``to_rows`` before batching);
    ``slots`` are each message's ``k`` hash slots (host-side
    :func:`~tpu_gossip.core.state.message_slots` over the payload hash,
    so live ingestion and pure-sim replay agree by construction).
    Entries at index >= ``count`` are dead padding. ``overflow`` bills
    arrivals the window could not fit (carried to the next batch by the
    frontend, never dropped).
    """

    origins: jax.Array  # (j,) int32 — state rows, dead entries 0
    slots: jax.Array  # (j, k) int32 — hash slots, dead entries 0
    count: jax.Array  # () int32 — live entries this round
    overflow: jax.Array  # () int32 — arrivals deferred to the next window


class IngestTelemetry(NamedTuple):
    """Per-round ingest counters for RoundStats (all scalar int32)."""

    offered: jax.Array  # arrivals presented to the device this round
    injected: jax.Array  # arrivals that landed (live origin, not suppressed)
    conflated: jax.Array  # k=1: landed on a live lease; k>=2: Bloom-FP suppressed
    overflow: jax.Array  # arrivals deferred past this round's window


def empty_batch(plan: IngestPlan) -> InjectBatch:
    """The zero-arrival batch — the quiescent round's injection input.
    Landing it is bit-identical to ``inject=None`` (test-pinned)."""
    j, k = plan.max_inject, plan.k_hashes
    return InjectBatch(
        origins=jnp.zeros((j,), dtype=jnp.int32),
        slots=jnp.zeros((j, k), dtype=jnp.int32),
        count=jnp.zeros((), dtype=jnp.int32),
        overflow=jnp.zeros((), dtype=jnp.int32),
    )


def make_batch(
    plan: IngestPlan,
    origins,
    payload_hashes,
    *,
    overflow: int = 0,
) -> InjectBatch:
    """Host-side batch builder: pad ``origins``/``payload_hashes`` (one
    per accepted arrival, arrival order — landing is sequential, so
    order is part of the trace) to the plan's static shape. Callers
    with more than ``max_inject`` arrivals split the excess into the
    NEXT window themselves and bill it here as ``overflow``."""
    origins = np.asarray(origins, dtype=np.int64)
    hashes = list(payload_hashes)
    if origins.ndim != 1 or origins.shape[0] != len(hashes):
        raise IngestError(
            f"origins {origins.shape} and payload_hashes ({len(hashes)}) "
            "must be parallel 1-D sequences"
        )
    n_arr = origins.shape[0]
    if n_arr > plan.max_inject:
        raise IngestError(
            f"{n_arr} arrivals exceed max_inject={plan.max_inject}; carry "
            "the excess into the next window and bill it as overflow="
        )
    j, k = plan.max_inject, plan.k_hashes
    o = np.zeros(j, dtype=np.int32)
    o[:n_arr] = origins
    s = np.zeros((j, k), dtype=np.int32)
    for i, h in enumerate(hashes):
        s[i] = message_slots(h, plan.msg_slots, k)
    return InjectBatch(
        origins=jnp.asarray(o),
        slots=jnp.asarray(s),
        count=jnp.asarray(n_arr, dtype=jnp.int32),
        overflow=jnp.asarray(int(overflow), dtype=jnp.int32),
    )


def apply_arrivals(
    batch: InjectBatch,
    rnd: jax.Array,
    *,
    seen: jax.Array,
    infected_round: jax.Array,
    slot_lease: jax.Array,
    exists: jax.Array,
    alive: jax.Array,
    declared_dead: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, IngestTelemetry]:
    """Land one round window's arrivals; returns (seen, infected_round,
    slot_lease, telemetry).

    The deterministic twin of :func:`~tpu_gossip.traffic.engine.
    apply_stream`'s landing half — SAME sequential lease scan, SAME
    conflation/Bloom rules, SAME saturated int16 lease writes — with the
    draws replaced by the batch's data. Consumes NO randomness (no salt,
    no fold), so composing it with any stochastic plane moves no
    existing stream. Runs AFTER the fused tail and the row stages, so
    origins are gated on the round's FINAL liveness (a client whose
    mapped peer is down this round is offered-but-not-injected — exactly
    a user knocking on a dead peer) and a round-r arrival first
    transmits in round r+1.
    """
    n = exists.shape[0]
    j, k = batch.slots.shape

    live = jnp.arange(j) < batch.count
    safe_o = jnp.clip(batch.origins, 0, n - 1)
    ok = (
        live
        & exists[safe_o]
        & alive[safe_o]
        & ~declared_dead[safe_o]
    )

    # sequential landing over the batch — arrival i+1 sees the leases
    # arrival i took, the per-message semantics the closed-form
    # predictors (sim.metrics) assume; the scan carries only the (M,)
    # lease table
    def land(lease, x):
        sl, ok_i = x  # (k,) int32, scalar bool
        cur = lease[sl]
        leased = cur >= 0
        if k == 1:
            suppressed = jnp.zeros((), dtype=bool)
            conf = ok_i & leased[0]
        else:
            all_leased = jnp.all(leased)
            suppressed = all_leased
            conf = ok_i & all_leased
        landed = ok_i & ~suppressed
        # free slots among the message's k take the lease; live leases
        # keep their (older, smaller) round under max — saturated at
        # ROUND_CAP like every round-valued int16 plane write
        contrib = jnp.where(
            landed & ~leased, saturate_round(rnd, lease.dtype), -1
        ).astype(lease.dtype)
        lease = lease.at[sl].max(contrib)
        return lease, (landed, conf)

    slot_lease, (landed, conflated) = jax.lax.scan(
        land, slot_lease, (batch.slots, ok)
    )

    rows = jnp.where(landed, safe_o, n)
    inj = (
        jnp.zeros_like(seen)
        .at[
            jnp.broadcast_to(rows[:, None], (j, k)).reshape(-1),
            batch.slots.reshape(-1),
        ]
        .set(True, mode="drop")
    )
    seen = seen | inj
    infected_round = jnp.where(
        inj & (infected_round < 0),
        saturate_round(rnd, infected_round.dtype),
        infected_round,
    )

    telem = IngestTelemetry(
        offered=batch.count.astype(jnp.int32),
        injected=jnp.sum(landed, dtype=jnp.int32),
        conflated=jnp.sum(conflated, dtype=jnp.int32),
        overflow=batch.overflow.astype(jnp.int32),
    )
    return seen, infected_round, slot_lease, telem
