"""Segmented fixed-horizon runner: periodic checkpoints OUTSIDE the jit.

The engines' jitted loop entries (``simulate`` / ``simulate_dist`` /
``simulate_fleet``) scan a whole horizon on device; a checkpoint cannot
land inside that scan without breaking donation and the bit-identity
contract. But splitting the scan at a round boundary IS bit-identical —
every round is a pure function of the carried state, which is exactly
what the remat epoch loops have relied on since PR 1 and the mid-flight
cursor pins (``fault_held``, ``slot_lease``, ``control_lvl``,
``pipe_buf``, the growth cursor) guarantee for every composed plane. So
the driver runs the horizon as segments cut at ``--checkpoint-every``
boundaries, saves the state + the stats-so-far between segments (reads
happen BEFORE the next segment donates the buffers), and concatenates
per-segment stats into the one trajectory the summary reads — a resumed
run therefore produces the identical final state and identical integer
stats, crash or no crash.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "CheckpointPolicy",
    "next_cut",
    "host_stats",
    "concat_stats",
    "run_checkpointed",
]


@dataclasses.dataclass
class CheckpointPolicy:
    """The CLI's settled checkpointing config, threaded to every engine
    path. ``shards`` is the FILE-level shard count (a storage choice —
    see the resharding contract in ckpt/store.py); ``run_config`` lands
    in each manifest for ``run_sim resume`` to rebuild from."""

    every: int
    directory: str
    keep: int = 0
    shards: int = 1
    kind: str = "run"
    run_config: dict | None = None


def next_cut(cur: int, total: int, *periods: int) -> int:
    """Rounds from ``cur`` to the next boundary: the horizon end or any
    period's next multiple (0/None periods ignored)."""
    nxt = total
    for p in periods:
        if p:
            nxt = min(nxt, (cur // p + 1) * p)
    return nxt - cur


def host_stats(stats, ici=None) -> dict:
    """One segment's stats as host arrays, keyed by field name; an
    active transport's analytic ICI counters ride along under the
    ``ici__`` prefix so a resumed run's byte accounting stays exact."""
    out = {f: np.asarray(getattr(stats, f)) for f in stats._fields}
    if ici is not None:
        for f in ici._fields:
            out[f"ici__{f}"] = np.asarray(getattr(ici, f))
    return out


def concat_stats(parts: list[dict], round_axis: int = 0) -> dict:
    """Concatenate per-segment stats dicts along the round axis (axis 1
    for fleet-batched stats). Key sets must agree — a prefix saved by a
    run with a different stats schema is a config error, not a silent
    truncation."""
    if not parts:
        return {}
    keys = set(parts[0])
    for p in parts[1:]:
        if set(p) != keys:
            raise ValueError(
                "stats segments disagree on fields: "
                f"{sorted(keys ^ set(p))} — the checkpoint was written by "
                "an incompatible run configuration"
            )
    return {
        k: np.concatenate([p[k] for p in parts], axis=round_axis)
        for k in sorted(keys)
    }


def run_checkpointed(
    state,
    total_rounds: int,
    run_segment,
    *,
    policy: CheckpointPolicy | None = None,
    stats_prefix: dict | None = None,
    round_axis: int = 0,
    fold_every: int = 0,
    fold=None,
    log=None,
):
    """Drive ``state`` to ``total_rounds`` in checkpoint-boundary segments.

    ``run_segment(state, seg) -> (state, stats_dict)`` runs ``seg``
    rounds through the engine's jitted loop and returns HOST stats
    (:func:`host_stats`). ``fold`` (with ``fold_every``) is the remat
    epoch hook: called as ``fold(state) -> state`` at every
    ``fold_every`` multiple strictly inside the horizon, AFTER any
    coinciding checkpoint save — so a checkpoint at an epoch boundary
    holds the PRE-fold state and resume replays the fold
    deterministically (the shard engines' re-partition draws its seed
    from the fold index, which the round cursor determines).

    Returns ``(state, stats_dict)`` with the stats prefix (a resumed
    run's pre-crash trajectory) concatenated in front.
    """
    parts: list[dict] = []
    if stats_prefix is not None:
        parts.append(dict(stats_prefix))
    cur = _round_of(state)
    every = policy.every if policy is not None else 0
    # a resumed run landing ON a fold boundary replays the fold first —
    # the matching uninterrupted run folded right after writing the
    # checkpoint this state came from
    if fold is not None and fold_every and cur and cur % fold_every == 0 \
            and cur < total_rounds and stats_prefix is not None:
        state = fold(state)
    while cur < total_rounds:
        seg = next_cut(cur, total_rounds, every, fold_every)
        state, seg_stats = run_segment(state, seg)
        parts.append(seg_stats)
        cur += seg
        if policy is not None and every and cur % every == 0 \
                and cur < total_rounds:
            from tpu_gossip.ckpt.store import save_checkpoint

            save_checkpoint(
                policy.directory, state, step=cur,
                shards=policy.shards,
                stats=concat_stats(parts, round_axis),
                run_config=policy.run_config, kind=policy.kind,
                keep=policy.keep, log=log,
            )
        if fold is not None and fold_every and cur % fold_every == 0 \
                and cur < total_rounds:
            state = fold(state)
    return state, concat_stats(parts, round_axis)


def _round_of(state) -> int:
    r = np.asarray(state.round)
    return int(r if r.ndim == 0 else r.reshape(-1)[0])
