"""The on-disk checkpoint format: sharded atomic writes + a manifest gate.

One checkpoint is one directory::

    <dir>/ckpt-00000040/
        shard-00000-of-00008.npz   # rows [lo, hi) of every (N, ·) plane
        ...                        # + that range's CSR slice
        global.npz                 # (M,)/scalar planes, the PRNG key,
                                   # the CSR capacity tail   (kind "run")
        lane-00003-of-00016.npz    # one lane's FULL solo state (kind
                                   # "fleet" — per-lane recovery is just
                                   # loading one file)
        stats.npz                  # the per-round stats accumulated so
                                   # far (the resumed trajectory's prefix)
        MANIFEST.json              # written LAST: format version, round
                                   # cursor, per-file sha256 digests,
                                   # PLANES-declared dtypes/shapes, the
                                   # run config resume rebuilds from

Atomicity is rename-based: every file is written to a temp name in the
same directory, fsynced, then ``os.replace``d into place; the manifest
lands LAST (after a directory fsync), so a crash mid-write leaves a
directory WITHOUT a complete manifest — by definition torn, skipped at
recovery. Integrity is digest-based: the manifest records each file's
sha256; a truncated shard, a flipped byte or a swapped file fails
verification and the recovery scan rolls back to the previous complete
checkpoint with a logged reason.

Resharding contract: the S shard files are row SLICES of the one global
state layout (the layout itself is set by the run's plan at build time
and recorded in the manifest), so the file-level shard count is a
storage choice, not a run constraint — an S-shard checkpoint
concatenates into the global state and restores into S′ shards for any
compatible run layout, including S′=1: the sharded-matching layout's
s=1 layout-truth contract run in reverse (sharded save → local load is
bit-identical, conformance-tested at small n in tests/sim/test_ckpt.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import re
import shutil
from pathlib import Path

import numpy as np

__all__ = [
    "CheckpointError",
    "MANIFEST_NAME",
    "FORMAT_VERSION",
    "checkpoint_name",
    "save_checkpoint",
    "verify_checkpoint",
    "list_checkpoint_steps",
    "latest_complete",
    "load_checkpoint",
    "load_any",
    "prune_checkpoints",
]

MANIFEST_NAME = "MANIFEST.json"
# format 3 = PACKED storage (core/packed.py): the five (N, M) bool planes
# land as LSB-first uint8 words, the six (N,) bool masks as one shared
# uint8 ``flags`` word — a checkpoint byte is never wider than the PLANES
# registry's packed declaration. Format 2 (unpacked planes) stays fully
# readable; loading one decodes into the same state losslessly.
FORMAT_VERSION = 3
READABLE_FORMATS = (2, 3)
_CKPT_RE = re.compile(r"^ckpt-(\d{8})$")

# planes stored per shard file are exactly the registry's (N, ·)-leading
# shapes; the CSR pair is row-sliced specially; everything else rides
# global.npz. Derived from PLANES so a new plane cannot silently miss the
# checkpoint format (tests pin the partition).
_CSR_PLANES = ("row_ptr", "col_idx")


class CheckpointError(Exception):
    """A torn, corrupt, or structurally foreign checkpoint."""


def checkpoint_name(step: int) -> str:
    return f"ckpt-{step:08d}"


def _row_planes(packed: bool = False):
    from tpu_gossip.core.state import PLANES

    base = tuple(
        p.name for p in PLANES
        if p.shape.startswith("(N") and p.name not in _CSR_PLANES
    )
    if not packed:
        return base
    # packed storage: the six flag planes collapse into the shared (N,)
    # uint8 word; the bit planes keep their names (packed arrays)
    from tpu_gossip.core.packed import FLAG_PLANES

    return tuple(p for p in base if p not in FLAG_PLANES) + ("flags",)


def _global_planes():
    from tpu_gossip.core.state import PLANES

    return tuple(
        p.name for p in PLANES
        if not p.shape.startswith("(N") and p.name not in _CSR_PLANES
    )


def _key_data(leaf):
    import jax

    return np.asarray(jax.random.key_data(leaf))


def _npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _atomic_write(path: Path, payload: bytes) -> dict:
    """temp-file + fsync + atomic rename; returns the manifest file entry."""
    tmp = path.with_name(f".tmp-{path.name}.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return {
        "sha256": hashlib.sha256(payload).hexdigest(),
        "bytes": len(payload),
    }


def _fsync_dir(path: Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — exotic filesystems
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _state_to_host(state) -> dict:
    """Every leaf as a host array (PRNG keys via their raw key data)."""
    out = {}
    for f in dataclasses.fields(type(state)):
        if f.name == "msg_slots":  # PackedSwarm's static width field
            continue
        leaf = getattr(state, f.name)
        if _is_key(leaf):
            out[f.name] = _key_data(leaf)
        else:
            out[f.name] = np.asarray(leaf)
    return out


def _pack_host(host: dict) -> dict:
    """Format-3 encode: the ONE shared host codec (core/packed.py —
    bit-for-bit the same words a PackedSwarm carry holds; save_swarm's
    legacy npz writes through the same helper, so the formats cannot
    drift)."""
    from tpu_gossip.core.packed import pack_host_planes

    return pack_host_planes(host)


def _unpack_host(arrays: dict, m: int) -> dict:
    """Format-3 decode through the shared host codec (lossless; forged
    dtypes stay undecoded for the named-plane validator)."""
    from tpu_gossip.core.packed import decode_host_planes

    return decode_host_planes(arrays, m)


def _host_packed(state) -> tuple[dict, int]:
    """(packed host dict, msg_slots) for either state representation —
    a PackedSwarm's leaves ARE the storage layout already; a SwarmState
    packs through the numpy twins."""
    from tpu_gossip.core.packed import PackedSwarm

    if isinstance(state, PackedSwarm):
        return _state_to_host(state), int(state.msg_slots)
    host = _state_to_host(state)
    m = int(host["seen"].shape[-1])
    return _pack_host(host), m


def _is_key(leaf) -> bool:
    import jax
    import jax.numpy as jnp

    return hasattr(leaf, "dtype") and jnp.issubdtype(
        leaf.dtype, jax.dtypes.prng_key
    )


def save_checkpoint(
    directory,
    state,
    *,
    step: int,
    shards: int = 1,
    stats: dict | None = None,
    run_config: dict | None = None,
    kind: str = "run",
    keep: int = 0,
    log=None,
) -> Path:
    """Write one complete checkpoint of ``state`` at round ``step``.

    ``kind="run"`` shards the peer axis over ``shards`` files (each file
    carries rows [lo, hi) of every (N, ·) plane plus that range's CSR
    slice). ``kind="fleet"`` takes a :func:`stack_states` batch and
    writes one file per LANE — each file is a complete solo state, so
    per-lane recovery is loading one file. ``stats`` is a dict of host
    arrays (the per-round trajectory so far); ``run_config`` lands in
    the manifest verbatim (what ``run_sim resume`` rebuilds from).
    ``keep`` > 0 prunes all but the newest ``keep`` checkpoints AFTER
    the new manifest is durable.
    """
    directory = Path(directory)
    ckdir = directory / checkpoint_name(step)
    ckdir.mkdir(parents=True, exist_ok=True)
    for leftover in ckdir.glob(".tmp-*"):
        leftover.unlink()

    files: dict[str, dict] = {}
    manifest: dict = {
        "format": FORMAT_VERSION,
        "kind": kind,
        "round": int(step),
        "files": files,
    }

    if kind == "fleet":
        lead = np.asarray(state.round).shape
        if len(lead) != 1:
            raise CheckpointError(
                "kind='fleet' expects a stack_states batch (every leaf "
                f"with a leading lane axis); round has shape {lead}"
            )
        lanes = int(lead[0])
        manifest["lanes"] = lanes
        manifest["n_peers"] = int(state.seen.shape[1])
        m = int(state.seen.shape[2])
        manifest["msg_slots"] = m
        lane_hosts = []
        for k in range(lanes):
            lane_host = {}
            for f in dataclasses.fields(type(state)):
                leaf = getattr(state, f.name)
                if _is_key(leaf):
                    lane_host[f.name] = _key_data(leaf[k])
                else:
                    lane_host[f.name] = np.asarray(leaf[k])
            lane_hosts.append(_pack_host(lane_host))
        manifest["planes"] = {
            name: {"dtype": str(arr.dtype) if name != "rng" else "key",
                   "shape": [] if name == "rng" else list(arr.shape)}
            for name, arr in lane_hosts[0].items()
        }
        for k, lane_host in enumerate(lane_hosts):
            lane_arrays = {
                (f"prngkey_{p}" if p == "rng" else f"field_{p}"): arr
                for p, arr in lane_host.items()
            }
            name = f"lane-{k:05d}-of-{lanes:05d}.npz"
            entry = _atomic_write(ckdir / name, _npz_bytes(lane_arrays))
            entry["lane"] = k
            files[name] = entry
    elif kind == "run":
        host, m = _host_packed(state)
        n = host["flags"].shape[0]
        manifest["n_peers"] = int(n)
        manifest["msg_slots"] = m
        manifest["shards"] = int(shards)
        manifest["planes"] = {
            name: {"dtype": str(arr.dtype) if name != "rng" else "key",
                   "shape": list(arr.shape)}
            for name, arr in host.items()
        }
        rp = host["row_ptr"]
        e_real = int(rp[-1])
        bounds = np.linspace(0, n, int(shards) + 1).astype(int)
        row_planes = [p for p in _row_planes(packed=True) if p in host]
        for s in range(int(shards)):
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            arrays = {f"rows_{p}": host[p][lo:hi] for p in row_planes}
            # the CSR slice: absolute row_ptr entries [lo, hi] and the
            # real edges they span — stored verbatim, so concatenation
            # reproduces the exact bytes (the capacity tail past
            # row_ptr[-1] rides global.npz)
            arrays["rows_row_ptr"] = rp[lo:hi + 1]
            arrays["rows_col_idx"] = host["col_idx"][int(rp[lo]):int(rp[hi])]
            name = f"shard-{s:05d}-of-{int(shards):05d}.npz"
            entry = _atomic_write(ckdir / name, _npz_bytes(arrays))
            entry["rows"] = [lo, hi]
            files[name] = entry
        gl = {f"field_{p}": host[p] for p in _global_planes() if p != "rng"}
        gl["prngkey_rng"] = host["rng"]
        gl["col_tail"] = host["col_idx"][e_real:]
        files["global.npz"] = _atomic_write(ckdir / "global.npz",
                                            _npz_bytes(gl))
    else:
        raise CheckpointError(f"unknown checkpoint kind {kind!r}")

    if stats is not None:
        files["stats.npz"] = _atomic_write(
            ckdir / "stats.npz",
            _npz_bytes({k: np.asarray(v) for k, v in stats.items()}),
        )
    if run_config is not None:
        manifest["run"] = run_config

    # every payload is durable and digest-recorded — land the manifest
    # LAST so its presence IS the completeness marker
    _fsync_dir(ckdir)
    _atomic_write(ckdir / MANIFEST_NAME,
                  json.dumps(manifest, indent=1).encode())
    _fsync_dir(ckdir)
    _fsync_dir(directory)
    if log is not None:
        log(f"checkpoint: wrote {ckdir.name} "
            f"({sum(e['bytes'] for e in files.values())} bytes, "
            f"{len(files)} files)")
    if keep > 0:
        prune_checkpoints(directory, keep=keep, log=log)
    return ckdir


def list_checkpoint_steps(directory) -> list[tuple[int, Path]]:
    """All ckpt-* entries under ``directory``, NEWEST first (no
    verification — that is :func:`latest_complete`'s job)."""
    directory = Path(directory)
    out = []
    if not directory.is_dir():
        return out
    for child in directory.iterdir():
        m = _CKPT_RE.match(child.name)
        if m and child.is_dir():
            out.append((int(m.group(1)), child))
    out.sort(key=lambda t: t[0], reverse=True)
    return out


def verify_checkpoint(path) -> dict:
    """Return the manifest iff the checkpoint is complete and digest-clean;
    raise :class:`CheckpointError` naming the failure otherwise."""
    path = Path(path)
    mpath = path / MANIFEST_NAME
    if not mpath.is_file():
        raise CheckpointError(
            f"{path.name}: no {MANIFEST_NAME} — torn write (the manifest "
            "lands last; a crash mid-save leaves none)"
        )
    try:
        manifest = json.loads(mpath.read_text())
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointError(
            f"{path.name}: unreadable manifest ({e}) — torn write"
        ) from e
    if manifest.get("format") not in READABLE_FORMATS:
        raise CheckpointError(
            f"{path.name}: manifest format {manifest.get('format')!r} "
            f"(this build reads {READABLE_FORMATS})"
        )
    for name, entry in manifest.get("files", {}).items():
        fpath = path / name
        if not fpath.is_file():
            raise CheckpointError(
                f"{path.name}: shard file {name} missing — dropped mid-write"
            )
        payload = fpath.read_bytes()
        if len(payload) != entry["bytes"]:
            raise CheckpointError(
                f"{path.name}: {name} holds {len(payload)} bytes, manifest "
                f"says {entry['bytes']} — truncated"
            )
        if hashlib.sha256(payload).hexdigest() != entry["sha256"]:
            raise CheckpointError(
                f"{path.name}: {name} sha256 mismatch — corrupted"
            )
    return manifest


def latest_complete(directory, log=None) -> tuple[Path, dict]:
    """Newest complete checkpoint under ``directory``, rolling back past
    torn/corrupt ones with a logged reason per skip."""
    steps = list_checkpoint_steps(directory)
    if not steps:
        raise CheckpointError(f"no checkpoints under {directory}")
    for _step, path in steps:
        try:
            manifest = verify_checkpoint(path)
        except CheckpointError as e:
            if log is not None:
                log(f"checkpoint: rolling back past {path.name}: {e}")
            continue
        return path, manifest
    raise CheckpointError(
        f"no COMPLETE checkpoint under {directory} — every candidate was "
        "torn or corrupt (reasons logged above)"
    )


def _load_npz(path: Path) -> dict:
    with np.load(path) as data:
        return {k: data[k] for k in data.files}


def load_checkpoint(path, *, lane: int | None = None,
                    manifest: dict | None = None):
    """Load one verified checkpoint directory.

    Returns ``(state, stats, manifest)`` — ``state`` a
    :class:`~tpu_gossip.core.state.SwarmState` (the concatenated global
    layout for kind "run"; for kind "fleet" the re-stacked batch, or
    lane ``lane`` as a SOLO state when given), ``stats`` the stored
    trajectory prefix as a dict of host arrays (None if the checkpoint
    carries none). Digests are verified before any bytes are trusted;
    pass the ``manifest`` :func:`latest_complete` already verified to
    skip the second full read+hash pass (recovery of a multi-GB
    checkpoint should not pay its I/O twice). Restored planes pass the
    PLANES dtype/shape validation (core.state.validate_state_planes),
    so a stale or foreign file fails HERE with a named plane, not
    inside jit.
    """
    import jax
    import jax.numpy as jnp

    from tpu_gossip.core.state import (
        SwarmState,
        cast_to_declared,
        stack_states,
        validate_state_planes,
    )

    path = Path(path)
    if manifest is None:
        manifest = verify_checkpoint(path)
    kind = manifest.get("kind", "run")
    packed_fmt = manifest.get("format", 2) >= 3

    def build_solo(arrays: dict, source: str) -> SwarmState:
        from tpu_gossip.core.state import zero_suspicion

        if "field_flags" in arrays:
            # packed payload (format 3): decode the flags word + the bit
            # planes back into the unpacked plane set — lossless, the
            # exact inverse of the save-side codec
            arrays = _unpack_host(arrays, int(manifest["msg_slots"]))
        kwargs = {}
        suspicion = ("suspect_round", "suspect_mark", "quarantine")
        for f in dataclasses.fields(SwarmState):
            if f"prngkey_{f.name}" in arrays:
                kwargs[f.name] = jax.random.wrap_key_data(
                    jnp.asarray(arrays[f"prngkey_{f.name}"])
                )
            elif f"field_{f.name}" in arrays:
                kwargs[f.name] = jnp.asarray(arrays[f"field_{f.name}"])
            elif f.name in suspicion:
                continue  # pre-adversarial checkpoint: filled below
            else:
                raise CheckpointError(
                    f"{source}: plane {f.name!r} missing from the "
                    "checkpoint — foreign or pre-format file"
                )
        absent = [p for p in suspicion if p not in kwargs]
        if len(absent) == len(suspicion):
            # checkpoints written before the quorum-defense planes load
            # with them zeroed — no suspicion in flight, no strikes,
            # nobody quarantined: exactly their semantics when saved
            kwargs.update(zero_suspicion(kwargs["exists"].shape[0]))
        elif absent:
            # a PARTIAL subset is not a pre-format file — it is a torn or
            # foreign checkpoint; zero-filling would silently drop the
            # planes that ARE stored
            raise CheckpointError(
                f"{source}: suspicion plane(s) {absent} missing while "
                f"{sorted(set(suspicion) - set(absent))} are present — "
                "torn or foreign checkpoint (a pre-adversarial file "
                "carries none of the three)"
            )
        kwargs = cast_to_declared(kwargs)
        state = SwarmState(**kwargs)
        validate_state_planes(state, source=source)
        return state

    if kind == "fleet":
        lanes = int(manifest["lanes"])
        lane_files = sorted(
            (e["lane"], name) for name, e in manifest["files"].items()
            if "lane" in e
        )
        if len(lane_files) != lanes:
            raise CheckpointError(
                f"{path.name}: manifest declares {lanes} lanes but lists "
                f"{len(lane_files)} lane files"
            )
        if lane is not None:
            if not (0 <= lane < lanes):
                raise CheckpointError(
                    f"{path.name}: lane {lane} outside [0, {lanes})"
                )
            name = dict((k, n) for k, n in lane_files)[lane]
            state = build_solo(_load_npz(path / name), f"{path.name}/{name}")
        else:
            state = stack_states([
                build_solo(_load_npz(path / name), f"{path.name}/{name}")
                for _k, name in lane_files
            ])
    else:
        shard_files = sorted(
            (e["rows"][0], e["rows"][1], name)
            for name, e in manifest["files"].items() if "rows" in e
        )
        if not shard_files:
            raise CheckpointError(f"{path.name}: manifest lists no shard files")
        gl = _load_npz(path / "global.npz")
        parts = [_load_npz(path / name) for _lo, _hi, name in shard_files]
        covered = 0
        for (lo, hi, name) in shard_files:
            if lo != covered:
                raise CheckpointError(
                    f"{path.name}: shard rows are not contiguous at {name} "
                    f"(expected [{covered}, ...), got [{lo}, {hi}))"
                )
            covered = hi
        if covered != int(manifest["n_peers"]):
            raise CheckpointError(
                f"{path.name}: shard files cover {covered} rows, manifest "
                f"declares n_peers={manifest['n_peers']}"
            )
        arrays = {}
        for p in _row_planes(packed=packed_fmt):
            arrays[f"field_{p}"] = np.concatenate(
                [part[f"rows_{p}"] for part in parts], axis=0
            )
        # CSR reassembly: absolute row_ptr slices overlap by one entry at
        # each boundary; the capacity tail (past row_ptr[-1]) comes back
        # from global.npz — stored verbatim, so the reassembled pair is
        # byte-identical to the saved one
        rp_parts = [parts[0]["rows_row_ptr"]] + [
            part["rows_row_ptr"][1:] for part in parts[1:]
        ]
        arrays["field_row_ptr"] = np.concatenate(rp_parts, axis=0)
        arrays["field_col_idx"] = np.concatenate(
            [part["rows_col_idx"] for part in parts] + [gl["col_tail"]],
            axis=0,
        )
        for key, val in gl.items():
            if key == "col_tail":
                continue
            arrays[key] = val
        state = build_solo(arrays, path.name)

    stats = None
    if "stats.npz" in manifest.get("files", {}):
        stats = _load_npz(path / "stats.npz")
    return state, stats, manifest


def load_any(path, *, lane: int | None = None):
    """Load a checkpoint from either world: a manifest directory (the
    durable format) or a bare ``.npz`` (BOTH legacy flat formats — the
    v1 positional layout and the pre-plane named layout — via
    ``core.state.load_swarm``, which applies the same declared-width
    casts and plane validation). Returns ``(state, stats, manifest)``;
    legacy files carry no stats prefix and a synthetic manifest."""
    path = Path(path)
    if path.is_dir():
        if (path / MANIFEST_NAME).is_file() or _CKPT_RE.match(path.name):
            return load_checkpoint(path, lane=lane)
        ck, _manifest = latest_complete(path)
        return load_checkpoint(ck, lane=lane)
    from tpu_gossip.core.state import load_swarm

    state = load_swarm(path)
    return state, None, {
        "format": "legacy-npz", "kind": "run",
        "round": int(np.asarray(state.round)),
    }


def prune_checkpoints(directory, *, keep: int, log=None) -> list[Path]:
    """Delete all but the newest ``keep`` checkpoint directories (torn
    ones older than the kept set included — they are unusable by
    definition). Returns the deleted paths."""
    if keep <= 0:
        return []
    steps = list_checkpoint_steps(directory)
    doomed = [path for _step, path in steps[keep:]]
    for path in doomed:
        shutil.rmtree(path, ignore_errors=True)
        if log is not None:
            log(f"checkpoint: pruned {path.name} (keep={keep})")
    return doomed
