"""Durability fault injection: corrupt a checkpoint the way hardware does.

The recovery contract is only as strong as the failure modes it is
tested against. This module is the test harness's (and the
recovery-smoke CI job's) way of manufacturing each mode
deterministically against a REAL checkpoint directory:

- ``truncate_shard`` — a shard file loses its tail (power loss between
  write and fsync on a weaker store, or a copy cut short).
- ``flip_byte``     — one byte flips mid-file (bit rot; a bad sector
  remap; a buggy transfer).
- ``drop_manifest`` — the manifest vanishes (the torn-write signature:
  a crash before the final rename leaves exactly this state).
- ``drop_shard``    — a whole shard file vanishes mid-write (crash
  between two shard renames).

Every mode must be DETECTED at recovery (``verify_checkpoint`` fails
with a named reason) and ROLLED BACK past (``latest_complete`` selects
the previous complete checkpoint) — never loaded.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from tpu_gossip.ckpt.store import MANIFEST_NAME, CheckpointError

__all__ = ["CORRUPTION_MODES", "corrupt_checkpoint"]

CORRUPTION_MODES = (
    "truncate_shard", "flip_byte", "drop_manifest", "drop_shard",
)


def _payload_files(ckdir: Path) -> list[Path]:
    manifest = json.loads((ckdir / MANIFEST_NAME).read_text())
    names = sorted(manifest.get("files", {}))
    return [ckdir / n for n in names]


def corrupt_checkpoint(
    ckdir, mode: str, *, index: int = 0, seed: int = 0
) -> Path:
    """Apply one corruption ``mode`` to the checkpoint at ``ckdir``.

    ``index`` picks the payload file (manifest order) for the file-level
    modes; ``seed`` picks the flipped byte's offset deterministically.
    Returns the path that was damaged.
    """
    ckdir = Path(ckdir)
    if mode not in CORRUPTION_MODES:
        raise ValueError(
            f"unknown corruption mode {mode!r}; choose from "
            f"{CORRUPTION_MODES}"
        )
    if mode == "drop_manifest":
        target = ckdir / MANIFEST_NAME
        if not target.is_file():
            raise CheckpointError(f"{ckdir} has no manifest to drop")
        target.unlink()
        return target
    files = _payload_files(ckdir)
    if not files:
        raise CheckpointError(f"{ckdir} lists no payload files")
    target = files[index % len(files)]
    if mode == "drop_shard":
        target.unlink()
        return target
    payload = bytearray(target.read_bytes())
    if not payload:
        raise CheckpointError(f"{target} is empty — nothing to corrupt")
    if mode == "truncate_shard":
        del payload[len(payload) // 2:]
    else:  # flip_byte
        # deterministic offset from the seed; avoid offset 0 so the npz
        # magic stays plausible and the DIGEST, not a parser error, is
        # what must catch it
        offset = 1 + (seed * 2654435761) % (len(payload) - 1)
        payload[offset] ^= 0x40
    tmp = target.with_name(f".tmp-chaos-{target.name}")
    tmp.write_bytes(bytes(payload))
    os.replace(tmp, target)
    return target
