"""Durable checkpoints: sharded atomic writes, torn-write detection,
bit-exact crash recovery.

The reference loses everything on a crash — a dead seed rebuilds its
registry from ``config.txt`` and a dead peer simply re-bootstraps
(SURVEY.md §5.4). The flat ``save_swarm``/``load_swarm`` path
(core/state.py) already made resume *possible*; this package makes it
*durable* and *production-shaped*:

- :mod:`tpu_gossip.ckpt.store` — the on-disk format: each shard's row
  slice of every addressable plane in its own file (temp-file + atomic
  rename), a manifest written LAST carrying format version, round
  cursor, per-file sha256 digests and the PLANES-declared dtypes/shapes.
  A checkpoint without a complete, digest-clean manifest is by
  definition torn and is skipped at recovery time.
- :mod:`tpu_gossip.ckpt.driver` — the segmented fixed-horizon runner:
  periodic in-run checkpointing OUTSIDE the jitted horizon at segment
  boundaries (donation and the bit-identity contract untouched),
  retention pruning, and the stats-prefix concatenation that makes a
  resumed trajectory equal the uninterrupted one bit for bit.
- :mod:`tpu_gossip.ckpt.chaos` — the durability fault injector the
  tests and the recovery-smoke CI job drive: truncated shards, flipped
  bytes, deleted manifests, dropped shards.

See docs/checkpointing.md for the format, the atomicity/torn-write
semantics, the resharding contract and the determinism contract.
"""

from tpu_gossip.ckpt.chaos import CORRUPTION_MODES, corrupt_checkpoint
from tpu_gossip.ckpt.driver import (
    CheckpointPolicy,
    concat_stats,
    host_stats,
    next_cut,
    run_checkpointed,
)
from tpu_gossip.ckpt.store import (
    MANIFEST_NAME,
    CheckpointError,
    checkpoint_name,
    latest_complete,
    list_checkpoint_steps,
    load_any,
    load_checkpoint,
    prune_checkpoints,
    save_checkpoint,
    verify_checkpoint,
)

__all__ = [
    "CheckpointError",
    "CheckpointPolicy",
    "CORRUPTION_MODES",
    "MANIFEST_NAME",
    "checkpoint_name",
    "concat_stats",
    "corrupt_checkpoint",
    "host_stats",
    "latest_complete",
    "list_checkpoint_steps",
    "load_any",
    "load_checkpoint",
    "next_cut",
    "prune_checkpoints",
    "run_checkpointed",
    "save_checkpoint",
    "verify_checkpoint",
]
